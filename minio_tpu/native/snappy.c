/* Snappy block-format codec + CRC32C — the native compression engine
 * behind the S2-style framed object compression (the reference vendors
 * klauspost/compress/s2, an assembly-accelerated snappy superset; this
 * implements the interoperable snappy subset of that format:
 * varint uncompressed length, then literal/copy tags).
 *
 * Exported (ctypes):
 *   size_t  mtpu_snappy_max_compressed(size_t n);
 *   size_t  mtpu_snappy_compress(const uint8_t*, size_t, uint8_t*);
 *   int64_t mtpu_snappy_uncompressed_length(const uint8_t*, size_t);
 *   int64_t mtpu_snappy_decompress(const uint8_t*, size_t,
 *                                  uint8_t*, size_t);
 *   uint32_t mtpu_crc32c(const uint8_t*, size_t);
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* ---------------- varint ---------------- */

static size_t put_varint(uint8_t *dst, uint64_t v) {
    size_t i = 0;
    while (v >= 0x80) {
        dst[i++] = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    dst[i++] = (uint8_t)v;
    return i;
}

static int64_t get_varint(const uint8_t *src, size_t n, uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    size_t i = 0;
    while (i < n && shift < 64) {
        uint8_t b = src[i++];
        v |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) {
            *out = v;
            return (int64_t)i;
        }
        shift += 7;
    }
    return -1;
}

/* ---------------- compression ---------------- */

#define HASH_BITS 14
#define HASH_SIZE (1u << HASH_BITS)
#define BLOCK 65536u
#define MIN_MATCH 4u

static inline uint32_t load32(const uint8_t *p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

static inline uint32_t hash32(uint32_t v) {
    return (v * 0x1e35a7bdu) >> (32 - HASH_BITS);
}

size_t mtpu_snappy_max_compressed(size_t n) {
    /* worst case: all literals, one tag per 2^32 run + varint header */
    return 32 + n + n / 6;
}

static uint8_t *emit_literal(uint8_t *d, const uint8_t *src, size_t len) {
    while (len > 0) {
        size_t run = len;
        if (run > (1u << 16)) run = 1u << 16; /* keep extras <= 2 bytes */
        size_t l = run - 1;
        if (l < 60) {
            *d++ = (uint8_t)(l << 2);
        } else if (l < 256) {
            *d++ = 60 << 2;
            *d++ = (uint8_t)l;
        } else {
            *d++ = 61 << 2;
            *d++ = (uint8_t)(l & 0xff);
            *d++ = (uint8_t)(l >> 8);
        }
        memcpy(d, src, run);
        d += run;
        src += run;
        len -= run;
    }
    return d;
}

static inline uint8_t *emit_copy_one(uint8_t *d, size_t offset,
                                     size_t len) {
    *d++ = (uint8_t)(((len - 1) << 2) | 2);
    *d++ = (uint8_t)(offset & 0xff);
    *d++ = (uint8_t)(offset >> 8);
    return d;
}

static uint8_t *emit_copy(uint8_t *d, size_t offset, size_t len) {
    /* 2-byte-offset copies, length 1..64 per tag. Split so the FINAL
     * tag is always >= 4 bytes: a naive 64-at-a-time loop strands a
     * 1..3-byte remainder the caller has already consumed (canonical
     * snappy emitCopy does the same 68/64+60 dance). len >= 4 here. */
    while (len >= 68) {
        d = emit_copy_one(d, offset, 64);
        len -= 64;
    }
    if (len > 64) {
        d = emit_copy_one(d, offset, 60);
        len -= 60;
    }
    return emit_copy_one(d, offset, len); /* 4..64 guaranteed */
}

size_t mtpu_snappy_compress(const uint8_t *src, size_t n, uint8_t *dst) {
    uint8_t *d = dst;
    d += put_varint(d, n);
    static __thread uint16_t table[HASH_SIZE];
    size_t base = 0;
    while (base < n) {
        size_t block_end = base + BLOCK;
        if (block_end > n) block_end = n;
        size_t blen = block_end - base;
        if (blen < MIN_MATCH + 4) {
            d = emit_literal(d, src + base, blen);
            base = block_end;
            continue;
        }
        memset(table, 0, sizeof(table));
        const uint8_t *b = src + base;
        size_t pos = 0, lit_start = 0;
        size_t limit = blen - MIN_MATCH;
        while (pos <= limit) {
            uint32_t h = hash32(load32(b + pos));
            size_t cand = table[h];
            table[h] = (uint16_t)pos;
            if (cand < pos && pos - cand <= 0xffff &&
                load32(b + cand) == load32(b + pos)) {
                /* extend the match */
                size_t mlen = MIN_MATCH;
                while (pos + mlen < blen &&
                       b[cand + mlen] == b[pos + mlen] && mlen < 0xffff)
                    mlen++;
                if (pos > lit_start)
                    d = emit_literal(d, b + lit_start, pos - lit_start);
                d = emit_copy(d, pos - cand, mlen);
                /* seed a couple of hashes inside the match for future
                 * back-references, then skip past it */
                size_t seed_end = pos + mlen;
                size_t s = pos + 1;
                for (; s + MIN_MATCH <= seed_end && s <= limit && s < pos + 4;
                     s++)
                    table[hash32(load32(b + s))] = (uint16_t)s;
                pos += mlen;
                lit_start = pos;
            } else {
                pos++;
            }
        }
        if (blen > lit_start)
            d = emit_literal(d, b + lit_start, blen - lit_start);
        base = block_end;
    }
    return (size_t)(d - dst);
}

/* ---------------- decompression ---------------- */

int64_t mtpu_snappy_uncompressed_length(const uint8_t *src, size_t n) {
    uint64_t v;
    if (get_varint(src, n, &v) < 0) return -1;
    return (int64_t)v;
}

int64_t mtpu_snappy_decompress(const uint8_t *src, size_t n,
                               uint8_t *dst, size_t dst_cap) {
    uint64_t want;
    int64_t hdr = get_varint(src, n, &want);
    if (hdr < 0 || want > dst_cap) return -1;
    size_t i = (size_t)hdr, o = 0;
    while (i < n) {
        uint8_t tag = src[i++];
        uint32_t kind = tag & 3;
        if (kind == 0) { /* literal */
            size_t len = (tag >> 2) + 1;
            if (len > 60) {
                size_t extra = len - 60; /* 1..4 extra length bytes */
                if (i + extra > n) return -1;
                len = 0;
                for (size_t k = 0; k < extra; k++)
                    len |= (size_t)src[i + k] << (8 * k);
                len += 1;
                i += extra;
            }
            if (i + len > n || o + len > dst_cap) return -1;
            memcpy(dst + o, src + i, len);
            i += len;
            o += len;
        } else {
            size_t len, off;
            if (kind == 1) {
                len = ((tag >> 2) & 7) + 4;
                if (i >= n) return -1;
                off = ((size_t)(tag >> 5) << 8) | src[i++];
            } else if (kind == 2) {
                len = (tag >> 2) + 1;
                if (i + 2 > n) return -1;
                off = (size_t)src[i] | ((size_t)src[i + 1] << 8);
                i += 2;
            } else {
                len = (tag >> 2) + 1;
                if (i + 4 > n) return -1;
                off = (size_t)src[i] | ((size_t)src[i + 1] << 8) |
                      ((size_t)src[i + 2] << 16) |
                      ((size_t)src[i + 3] << 24);
                i += 4;
            }
            if (off == 0 || off > o || o + len > dst_cap) return -1;
            /* overlapping copies are the RLE mechanism: byte loop */
            for (size_t k = 0; k < len; k++) {
                dst[o] = dst[o - off];
                o++;
            }
        }
    }
    return (o == want) ? (int64_t)o : -1;
}

/* ---------------- CRC32C (Castagnoli) ---------------- */

static uint32_t crc32c_table[256];
static int crc32c_ready = 0;

static void crc32c_init(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
        crc32c_table[i] = c;
    }
    crc32c_ready = 1;
}

uint32_t mtpu_crc32c(const uint8_t *p, size_t n) {
    if (!crc32c_ready) crc32c_init();
    uint32_t c = 0xffffffffu;
    for (size_t i = 0; i < n; i++)
        c = crc32c_table[(c ^ p[i]) & 0xff] ^ (c >> 8);
    return c ^ 0xffffffffu;
}
