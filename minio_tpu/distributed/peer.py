"""Peer control plane + bootstrap: the node mesh used for cache
invalidation, cluster info collection, and the startup config-consistency
handshake — behavioral parity with the reference's cmd/peer-rest-server.go
/ cmd/peer-rest-client.go / cmd/notification.go (hub) and
cmd/bootstrap-peer-server.go (verifyServerSystemConfig).
"""

from __future__ import annotations

import os
import threading
import time

from .rest import RPCClient, RPCError, RPCServer

PEER_PREFIX = "/mtpu/peer/v1"
BOOTSTRAP_PREFIX = "/mtpu/bootstrap/v1"


class PeerRESTServer:
    """Serve this node's control-plane methods to the mesh."""

    def __init__(self, secret: str, host: str = "127.0.0.1", port: int = 0,
                 bucket_meta=None, iam=None, object_layer=None,
                 lockers=None, trace=None, logger=None):
        self.bucket_meta = bucket_meta
        self.iam = iam
        self.object_layer = object_layer
        self.lockers = lockers
        self.trace = trace
        self.logger = logger
        self._profiler = None
        self._prof_lock = threading.Lock()
        self.started_ns = time.time_ns()
        self.rpc = RPCServer(PEER_PREFIX, secret, host, port)
        for name in ("ping", "load_bucket_metadata", "delete_bucket_metadata",
                     "load_user", "load_policy", "server_info",
                     "local_storage_info", "get_locks", "signal_service",
                     "list_page", "bump_listing_gen",
                     "trace_poll", "start_profiling", "download_profiling",
                     "console_log"):
            self.rpc.register(name, getattr(self, f"_h_{name}"))

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    # --- handlers ---

    def _h_ping(self, args, body):
        return {"ok": True}

    def _h_load_bucket_metadata(self, args, body):
        if self.bucket_meta is not None:
            self.bucket_meta.invalidate(args["bucket"])
        return {}

    def _h_delete_bucket_metadata(self, args, body):
        if self.bucket_meta is not None:
            self.bucket_meta.invalidate(args["bucket"])
        return {}

    def _h_load_user(self, args, body):
        if self.iam is not None:
            self.iam.load()
        return {}

    def _h_load_policy(self, args, body):
        if self.iam is not None:
            self.iam.load()
        return {}

    def _h_server_info(self, args, body):
        return {
            "endpoint": self.endpoint,
            "uptime_ns": time.time_ns() - self.started_ns,
            "version": "minio-tpu/0.1",
            "pid": os.getpid(),
        }

    def _h_local_storage_info(self, args, body):
        if self.object_layer is None:
            return {"disks": []}
        disks = []
        for pool in getattr(self.object_layer, "pools", []):
            for d in pool.disks:
                if d is None:
                    continue
                try:
                    di = d.disk_info()
                    disks.append({
                        "endpoint": di.endpoint, "total": di.total,
                        "free": di.free, "used": di.used, "error": "",
                    })
                except Exception as exc:  # noqa: BLE001 - per-disk status
                    disks.append({"endpoint": d.endpoint(), "error": str(exc)})
        return {"disks": disks}

    def _h_get_locks(self, args, body):
        if self.lockers is None:
            return {"locks": {}}
        return {"locks": {
            res: [
                {"owner": g["owner"], "writer": g["writer"], "ts": g["ts"]}
                for g in self.lockers.held(res)
            ]
            for res in list(self.lockers._map)
        }}

    def _h_signal_service(self, args, body):
        # restart/stop signaling is a host-process concern; recorded only.
        return {"signal": args.get("signal", ""), "accepted": True}

    # --- metacache coordination (ref peerRESTMethodGetMetacacheListing;
    # --- see distributed/listing.py for the design) ---

    def _h_list_page(self, args, body):
        """Serve one listing page from THIS node's metacache — called by
        peers for listings this node owns."""
        ol = self.object_layer
        if ol is None or not hasattr(ol, "_metacache"):
            raise RuntimeError("no listing-capable object layer")
        bucket, prefix = args["bucket"], args.get("prefix", "")
        marker, count = args.get("marker", ""), int(args["count"])
        from ..object.metacache import StaleListingCache

        # Advance to at least the caller's generation: a node that just
        # wrote must never get a page older than its own write.
        caller_gen = int(args.get("gen", "0"))
        with ol._gen_lock:
            if ol._list_gen.get(bucket, 0) < caller_gen:
                ol._list_gen[bucket] = caller_gen
        while True:
            gen = ol._list_gen.get(bucket, 0)
            factory = ol._merged_stream_factory(bucket, prefix)
            try:
                entries, exhausted = ol._metacache.page(
                    bucket, prefix, gen, marker, count, factory
                )
                break
            except StaleListingCache:
                continue  # raced an invalidation; retry at the new gen
        return {
            "entries": [[n, bytes(b)] for n, b in entries],
            "exhausted": exhausted,
        }

    def _h_bump_listing_gen(self, args, body):
        """A peer mutated this bucket: move the local listing generation
        so caches built before the write die at the next page."""
        ol = self.object_layer
        if ol is not None and hasattr(ol, "invalidate_listings"):
            ol.invalidate_listings(args["bucket"])
        return {}

    # --- observability fan-in (ref peerRESTMethodTrace,
    # --- NotificationSys.StartProfiling cmd/notification.go:287,
    # --- peer /log console stream cmd/peer-rest-common.go:57) ---

    def _h_trace_poll(self, args, body):
        """Bounded poll of THIS node's trace bus for a mesh-wide
        `mc admin trace` (the reference streams; a poll window keeps the
        RPC plane request/response)."""
        if self.trace is None:
            return {"entries": []}
        import queue as _queue

        wait_s = min(float(args.get("wait", "1")), 10.0)
        q = self.trace.subscribe()
        out = []
        deadline = time.time() + wait_s
        try:
            while time.time() < deadline and len(out) < 1000:
                try:
                    out.append(q.get(
                        timeout=max(0.05, deadline - time.time())))
                except _queue.Empty:
                    break
        finally:
            self.trace.unsubscribe(q)
        return {"entries": out}

    def _h_start_profiling(self, args, body):
        from ..observability.profiler import SamplingProfiler

        with self._prof_lock:
            if self._profiler is not None and self._profiler.running:
                return {"status": "already running"}
            self._profiler = SamplingProfiler().start()
        return {"status": "started"}

    def _h_download_profiling(self, args, body):
        with self._prof_lock:
            prof, self._profiler = self._profiler, None
        if prof is None:
            return {"report": "", "running": False}
        return {"report": prof.stop_and_report(), "running": True}

    def _h_console_log(self, args, body):
        if self.logger is None:
            return {"entries": []}
        n = max(1, min(int(args.get("n", "100")), 1024))
        return {"entries": self.logger.recent(n)}


class PeerClient:
    """RPC client for one peer (ref cmd/peer-rest-client.go)."""

    def __init__(self, endpoint: str, secret: str):
        self.endpoint = endpoint
        self._c = RPCClient(endpoint, PEER_PREFIX, secret, timeout=10.0)

    def call(self, method: str, args: dict | None = None):
        return self._c.call(method, args)

    @property
    def online(self) -> bool:
        return self._c.online


class NotificationSys:
    """Fan-out hub over all peers (ref cmd/notification.go:1556 — the
    name is historical; it is the peer-broadcast mechanism)."""

    def __init__(self, peers: list[PeerClient]):
        self.peers = peers

    def _broadcast(self, method: str, args: dict | None = None) -> list:
        """Call every peer CONCURRENTLY (the reference fans out with one
        goroutine per peer; serial calls would stack trace-poll waits)."""
        from concurrent.futures import ThreadPoolExecutor

        if not self.peers:
            return []

        def one(p):
            try:
                return p.call(method, args)
            except RPCError as exc:
                return exc

        with ThreadPoolExecutor(max_workers=min(8, len(self.peers))) as ex:
            return list(ex.map(one, self.peers))

    def load_bucket_metadata(self, bucket: str):
        self._broadcast("load_bucket_metadata", {"bucket": bucket})

    def delete_bucket_metadata(self, bucket: str):
        self._broadcast("delete_bucket_metadata", {"bucket": bucket})

    def load_user(self):
        self._broadcast("load_user")

    def server_info(self) -> list[dict]:
        return [
            r for r in self._broadcast("server_info")
            if not isinstance(r, Exception)
        ]

    def storage_info(self) -> list[dict]:
        return [
            r for r in self._broadcast("local_storage_info")
            if not isinstance(r, Exception)
        ]

    def get_locks(self) -> list[dict]:
        return [
            r for r in self._broadcast("get_locks")
            if not isinstance(r, Exception)
        ]

    # --- observability fan-out (ref NotificationSys.StartProfiling,
    # --- DownloadProfilingData, peer trace subscribe) ---

    def trace_poll(self, wait_s: float = 1.0) -> list[dict]:
        """Merged trace entries from every peer's bus, time-ordered."""
        entries: list[dict] = []
        for r in self._broadcast("trace_poll", {"wait": str(wait_s)}):
            if not isinstance(r, Exception):
                entries.extend(r.get("entries", []))
        entries.sort(key=lambda e: e.get("time_ns", 0))
        return entries

    def start_profiling(self) -> dict:
        out = {}
        for p, r in zip(self.peers, self._broadcast("start_profiling")):
            out[p.endpoint] = (
                r.get("status") if not isinstance(r, Exception) else str(r)
            )
        return out

    def download_profiling(self) -> dict:
        """Per-node profile reports (the reference zips per-node pprof
        files, cmd/notification.go DownloadProfilingData)."""
        out = {}
        for p, r in zip(self.peers, self._broadcast("download_profiling")):
            if isinstance(r, Exception):
                out[p.endpoint] = f"error: {r}"
            elif r.get("running"):
                out[p.endpoint] = r.get("report", "")
        return out

    def console_log(self, n: int = 100) -> list[dict]:
        entries: list[dict] = []
        for p, r in zip(self.peers,
                        self._broadcast("console_log", {"n": str(n)})):
            if isinstance(r, Exception):
                continue
            for e in r.get("entries", []):
                e = dict(e)
                e["node"] = p.endpoint
                entries.append(e)
        entries.sort(key=lambda e: e.get("time", ""))
        return entries


class BootstrapServer:
    """Startup config handshake endpoint
    (ref cmd/bootstrap-peer-server.go:37 /verify)."""

    def __init__(self, secret: str, config: dict,
                 host: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.rpc = RPCServer(BOOTSTRAP_PREFIX, secret, host, port)
        self.rpc.register("verify", self._h_verify)

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    def _h_verify(self, args, body):
        return dict(self.config)


def verify_cluster_config(local_config: dict, peer_endpoints: list[str],
                          secret: str, retries: int = 30,
                          delay_s: float = 0.2) -> None:
    """Loop until every peer reports an identical config fingerprint
    (ref cmd/server-main.go:446-460 verifyServerSystemConfig loop).
    Raises RuntimeError on persistent mismatch/unreachable peers."""
    last_err = None
    for _ in range(retries):
        ok = True
        for ep in peer_endpoints:
            client = RPCClient(ep, BOOTSTRAP_PREFIX, secret, timeout=5.0)
            try:
                remote = client.call("verify")
            except RPCError as exc:
                ok = False
                last_err = f"{ep} unreachable: {exc}"
                break
            if remote != local_config:
                ok = False
                last_err = (
                    f"{ep} config mismatch: {remote} != {local_config}"
                )
                break
        if ok:
            return
        time.sleep(delay_s)
    raise RuntimeError(f"cluster config verification failed: {last_err}")
