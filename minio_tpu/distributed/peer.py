"""Peer control plane + bootstrap: the node mesh used for cache
invalidation, cluster info collection, and the startup config-consistency
handshake — behavioral parity with the reference's cmd/peer-rest-server.go
/ cmd/peer-rest-client.go / cmd/notification.go (hub) and
cmd/bootstrap-peer-server.go (verifyServerSystemConfig).
"""

from __future__ import annotations

import os
import time

from .rest import RPCClient, RPCError, RPCServer

PEER_PREFIX = "/mtpu/peer/v1"
BOOTSTRAP_PREFIX = "/mtpu/bootstrap/v1"


class PeerRESTServer:
    """Serve this node's control-plane methods to the mesh."""

    def __init__(self, secret: str, host: str = "127.0.0.1", port: int = 0,
                 bucket_meta=None, iam=None, object_layer=None,
                 lockers=None, trace=None):
        self.bucket_meta = bucket_meta
        self.iam = iam
        self.object_layer = object_layer
        self.lockers = lockers
        self.trace = trace
        self.started_ns = time.time_ns()
        self.rpc = RPCServer(PEER_PREFIX, secret, host, port)
        for name in ("ping", "load_bucket_metadata", "delete_bucket_metadata",
                     "load_user", "load_policy", "server_info",
                     "local_storage_info", "get_locks", "signal_service"):
            self.rpc.register(name, getattr(self, f"_h_{name}"))

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    # --- handlers ---

    def _h_ping(self, args, body):
        return {"ok": True}

    def _h_load_bucket_metadata(self, args, body):
        if self.bucket_meta is not None:
            self.bucket_meta.invalidate(args["bucket"])
        return {}

    def _h_delete_bucket_metadata(self, args, body):
        if self.bucket_meta is not None:
            self.bucket_meta.invalidate(args["bucket"])
        return {}

    def _h_load_user(self, args, body):
        if self.iam is not None:
            self.iam.load()
        return {}

    def _h_load_policy(self, args, body):
        if self.iam is not None:
            self.iam.load()
        return {}

    def _h_server_info(self, args, body):
        return {
            "endpoint": self.endpoint,
            "uptime_ns": time.time_ns() - self.started_ns,
            "version": "minio-tpu/0.1",
            "pid": os.getpid(),
        }

    def _h_local_storage_info(self, args, body):
        if self.object_layer is None:
            return {"disks": []}
        disks = []
        for pool in getattr(self.object_layer, "pools", []):
            for d in pool.disks:
                if d is None:
                    continue
                try:
                    di = d.disk_info()
                    disks.append({
                        "endpoint": di.endpoint, "total": di.total,
                        "free": di.free, "used": di.used, "error": "",
                    })
                except Exception as exc:  # noqa: BLE001 - per-disk status
                    disks.append({"endpoint": d.endpoint(), "error": str(exc)})
        return {"disks": disks}

    def _h_get_locks(self, args, body):
        if self.lockers is None:
            return {"locks": {}}
        return {"locks": {
            res: [
                {"owner": g["owner"], "writer": g["writer"], "ts": g["ts"]}
                for g in self.lockers.held(res)
            ]
            for res in list(self.lockers._map)
        }}

    def _h_signal_service(self, args, body):
        # restart/stop signaling is a host-process concern; recorded only.
        return {"signal": args.get("signal", ""), "accepted": True}


class PeerClient:
    """RPC client for one peer (ref cmd/peer-rest-client.go)."""

    def __init__(self, endpoint: str, secret: str):
        self.endpoint = endpoint
        self._c = RPCClient(endpoint, PEER_PREFIX, secret, timeout=10.0)

    def call(self, method: str, args: dict | None = None):
        return self._c.call(method, args)

    @property
    def online(self) -> bool:
        return self._c.online


class NotificationSys:
    """Fan-out hub over all peers (ref cmd/notification.go:1556 — the
    name is historical; it is the peer-broadcast mechanism)."""

    def __init__(self, peers: list[PeerClient]):
        self.peers = peers

    def _broadcast(self, method: str, args: dict | None = None) -> list:
        out = []
        for p in self.peers:
            try:
                out.append(p.call(method, args))
            except RPCError as exc:
                out.append(exc)
        return out

    def load_bucket_metadata(self, bucket: str):
        self._broadcast("load_bucket_metadata", {"bucket": bucket})

    def delete_bucket_metadata(self, bucket: str):
        self._broadcast("delete_bucket_metadata", {"bucket": bucket})

    def load_user(self):
        self._broadcast("load_user")

    def server_info(self) -> list[dict]:
        return [
            r for r in self._broadcast("server_info")
            if not isinstance(r, Exception)
        ]

    def storage_info(self) -> list[dict]:
        return [
            r for r in self._broadcast("local_storage_info")
            if not isinstance(r, Exception)
        ]

    def get_locks(self) -> list[dict]:
        return [
            r for r in self._broadcast("get_locks")
            if not isinstance(r, Exception)
        ]


class BootstrapServer:
    """Startup config handshake endpoint
    (ref cmd/bootstrap-peer-server.go:37 /verify)."""

    def __init__(self, secret: str, config: dict,
                 host: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.rpc = RPCServer(BOOTSTRAP_PREFIX, secret, host, port)
        self.rpc.register("verify", self._h_verify)

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    def _h_verify(self, args, body):
        return dict(self.config)


def verify_cluster_config(local_config: dict, peer_endpoints: list[str],
                          secret: str, retries: int = 30,
                          delay_s: float = 0.2) -> None:
    """Loop until every peer reports an identical config fingerprint
    (ref cmd/server-main.go:446-460 verifyServerSystemConfig loop).
    Raises RuntimeError on persistent mismatch/unreachable peers."""
    last_err = None
    for _ in range(retries):
        ok = True
        for ep in peer_endpoints:
            client = RPCClient(ep, BOOTSTRAP_PREFIX, secret, timeout=5.0)
            try:
                remote = client.call("verify")
            except RPCError as exc:
                ok = False
                last_err = f"{ep} unreachable: {exc}"
                break
            if remote != local_config:
                ok = False
                last_err = (
                    f"{ep} config mismatch: {remote} != {local_config}"
                )
                break
        if ok:
            return
        time.sleep(delay_s)
    raise RuntimeError(f"cluster config verification failed: {last_err}")
