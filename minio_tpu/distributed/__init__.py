"""Distributed substrate: the node-to-node RPC planes (storage, lock,
peer-control, bootstrap) that make multi-node erasure pools work —
reference: cmd/storage-rest-*.go, pkg/dsync, cmd/peer-rest-*.go,
cmd/bootstrap-peer-server.go."""

from .dsync import (
    DRWMutex,
    Dsync,
    LocalLocker,
    LockRESTServer,
)
from .peer import (
    BootstrapServer,
    NotificationSys,
    PeerClient,
    PeerRESTServer,
    verify_cluster_config,
)
from .rest import RPCClient, RPCError, RPCServer, make_token, verify_token
from .storage_rest import RemoteStorage, StorageRESTServer

__all__ = [
    "DRWMutex", "Dsync", "LocalLocker", "LockRESTServer",
    "BootstrapServer", "NotificationSys", "PeerClient", "PeerRESTServer",
    "verify_cluster_config",
    "RPCClient", "RPCError", "RPCServer", "make_token", "verify_token",
    "RemoteStorage", "StorageRESTServer",
]
