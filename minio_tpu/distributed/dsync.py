"""dsync: distributed read/write locks with quorum — behavioral parity
with the reference's pkg/dsync (DRWMutex quorum algorithm
pkg/dsync/drwmutex.go:347-464, auto-refresh :251, server-side expiry)
plus the lock RPC plane (cmd/lock-rest-server.go:93-232,
cmd/local-locker.go).

Algorithm: a lock is held when a majority (writes: tolerance = n//2,
quorum = n - tolerance; reads: quorum = n//2 + 1 when n even... the
reference uses tolerance = n/2 and for writes requires quorum+1 when
n == 2*tolerance) of lockers granted it. Partial grants are rolled back.
Holders refresh periodically; lockers expire stale entries so crashed
holders release automatically.
"""

from __future__ import annotations

import threading
import time
import uuid

from .rest import RPCClient, RPCError, RPCServer

LOCK_PREFIX = "/mtpu/lock/v1"
DEFAULT_EXPIRY_S = 30.0
REFRESH_INTERVAL_S = 10.0

# Acquisition fan-out pool (lock/rlock). LIVENESS traffic — refresh and
# unlock — deliberately does NOT share it: under an acquisition storm
# against a dead peer (5s timeouts saturating these workers) a queued
# refresh could miss the server-side expiry and silently lose a held
# write lock. Tasks never submit nested tasks, so bounded pools cannot
# deadlock.
from concurrent.futures import ThreadPoolExecutor as _TPE  # noqa: E402

_lock_pool = _TPE(max_workers=32, thread_name_prefix="mtpu-dsync")
# Refresh is the ONLY traffic on this pool: anything sharing it
# (acquires, unlocks) could queue 5s-timeout tasks ahead of the
# refreshes that keep held write locks alive past server-side expiry.
_refresh_pool = _TPE(max_workers=8, thread_name_prefix="mtpu-dsync-ref")
_unlock_pool = _TPE(max_workers=16, thread_name_prefix="mtpu-dsync-unl")

# Unlock RPCs that failed at the transport (peer dead/partitioned):
# each one leaks its grant server-side until lock expiry, invisibly
# extending holds. Counted here (module counter for tests) and exported
# as mtpu_dsync_unlock_failures_total when a registry is installed, so
# a leaked-lock storm shows up on the metrics endpoint instead of as
# mystery contention.
_metrics = None
UNLOCK_FAILURES = {"total": 0}
_unlock_fail_mu = threading.Lock()


def set_metrics(registry) -> None:
    global _metrics
    _metrics = registry


def _note_unlock_failures(n: int, resource: str) -> None:
    with _unlock_fail_mu:
        UNLOCK_FAILURES["total"] += n
    if _metrics is not None:
        _metrics.inc("dsync_unlock_failures_total", n)

# One shared refresher thread ticks every second over ALL held mutexes
# and refreshes each at ITS OWN cadence (the reference runs one
# goroutine per held lock; a registry + ticker gives the same
# semantics without a thread spawn on every millisecond-long op, while
# sub-10s expiry deployments/tests keep their fast refresh intervals).
_TICK_S = 1.0
_held_mu = threading.Lock()
_held: dict[int, "DRWMutex"] = {}
_refresher_on = False


def _register_held(mu: "DRWMutex"):
    global _refresher_on
    with _held_mu:
        _held[id(mu)] = mu
        if _refresher_on:
            return
        _refresher_on = True

    def tick():
        while True:
            time.sleep(_TICK_S)
            now = time.monotonic()
            with _held_mu:
                due = [
                    m for m in _held.values()
                    if (not m._refreshing
                        and now - m._last_refresh
                        >= m._refresh_interval)
                ]
                for m in due:
                    # In-flight dedup: a refresh stuck on dead peers
                    # (5s/peer serial) must not stack duplicates each
                    # tick and starve OTHER mutexes' refreshes.
                    m._refreshing = True
            for m in due:
                _refresh_pool.submit(m._do_refresh)

    threading.Thread(target=tick, daemon=True,
                     name="mtpu-dsync-refresh").start()


def _deregister_held(mu: "DRWMutex"):
    with _held_mu:
        _held.pop(id(mu), None)


class LocalLocker:
    """In-process lock table for one node (ref cmd/local-locker.go).

    Entries: resource -> list of grants {uid, owner, writer, ts}.
    """

    def __init__(self, expiry_s: float = DEFAULT_EXPIRY_S):
        self._mu = threading.Lock()
        self._map: dict[str, list[dict]] = {}
        self.expiry_s = expiry_s

    def _expire(self, now: float):
        for res in list(self._map):
            grants = [
                g for g in self._map[res]
                if now - g["ts"] < self.expiry_s
            ]
            if grants:
                self._map[res] = grants
            else:
                del self._map[res]

    def lock(self, resource: str, uid: str, owner: str) -> bool:
        now = time.time()
        with self._mu:
            self._expire(now)
            if resource in self._map:
                return False
            self._map[resource] = [
                {"uid": uid, "owner": owner, "writer": True, "ts": now}
            ]
            return True

    def rlock(self, resource: str, uid: str, owner: str) -> bool:
        now = time.time()
        with self._mu:
            self._expire(now)
            grants = self._map.get(resource, [])
            if any(g["writer"] for g in grants):
                return False
            grants.append(
                {"uid": uid, "owner": owner, "writer": False, "ts": now}
            )
            self._map[resource] = grants
            return True

    def unlock(self, resource: str, uid: str) -> bool:
        with self._mu:
            grants = self._map.get(resource)
            if not grants:
                return False
            kept = [g for g in grants if g["uid"] != uid]
            if len(kept) == len(grants):
                return False
            if kept:
                self._map[resource] = kept
            else:
                del self._map[resource]
            return True

    def refresh(self, resource: str, uid: str) -> bool:
        now = time.time()
        with self._mu:
            self._expire(now)
            for g in self._map.get(resource, []):
                if g["uid"] == uid:
                    g["ts"] = now
                    return True
            return False

    def force_unlock(self, resource: str) -> bool:
        with self._mu:
            return self._map.pop(resource, None) is not None

    def held(self, resource: str) -> list[dict]:
        with self._mu:
            self._expire(time.time())
            return list(self._map.get(resource, []))


class LockRESTServer:
    """Expose a LocalLocker on the lock RPC plane."""

    def __init__(self, secret: str, host: str = "127.0.0.1", port: int = 0,
                 expiry_s: float = DEFAULT_EXPIRY_S):
        self.locker = LocalLocker(expiry_s)
        self.rpc = RPCServer(LOCK_PREFIX, secret, host, port)
        for name in ("ping", "lock", "rlock", "unlock", "refresh",
                     "force_unlock"):
            self.rpc.register(name, getattr(self, f"_h_{name}"))

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    def _h_ping(self, args, body):
        return {"ok": True}

    def _h_lock(self, args, body):
        return {"ok": self.locker.lock(
            args["resource"], args["uid"], args.get("owner", "")
        )}

    def _h_rlock(self, args, body):
        return {"ok": self.locker.rlock(
            args["resource"], args["uid"], args.get("owner", "")
        )}

    def _h_unlock(self, args, body):
        return {"ok": self.locker.unlock(args["resource"], args["uid"])}

    def _h_refresh(self, args, body):
        return {"ok": self.locker.refresh(args["resource"], args["uid"])}

    def _h_force_unlock(self, args, body):
        return {"ok": self.locker.force_unlock(args["resource"])}


class _LockerClient:
    """One locker endpoint: either in-process (LocalLocker) or remote."""

    def __init__(self, local: LocalLocker | None = None,
                 endpoint: str = "", secret: str = ""):
        self._local = local
        self._client = (
            None if local is not None
            else RPCClient(endpoint, LOCK_PREFIX, secret, timeout=5.0)
        )

    def call(self, method: str, resource: str, uid: str, owner: str) -> bool:
        return self.call2(method, resource, uid, owner)[0]

    def call2(self, method: str, resource: str, uid: str,
              owner: str) -> tuple[bool, Exception | None]:
        """(ok, transport_error): a False with error=None means the
        peer ANSWERED no-grant; error!=None means the RPC itself failed
        — for unlock, the distinction between 'nothing to release' and
        'grant leaked until expiry'."""
        if self._local is not None:
            fn = getattr(self._local, method)
            if method == "force_unlock":
                return fn(resource), None
            if method in ("unlock", "refresh"):
                return fn(resource, uid), None
            return fn(resource, uid, owner), None
        try:
            return bool(self._client.call(method, {
                "resource": resource, "uid": uid, "owner": owner,
            })["ok"]), None
        except RPCError as exc:
            return False, exc


class DRWMutex:
    """Distributed RW mutex over N lockers with quorum + refresh
    (ref pkg/dsync/drwmutex.go:56)."""

    def __init__(self, lockers: list[_LockerClient], resource: str,
                 owner: str = "", refresh_interval: float = REFRESH_INTERVAL_S):
        self.lockers = lockers
        self.resource = resource
        self.owner = owner or str(uuid.uuid4())
        self.uid = ""
        self._writer = False
        self._refresh_interval = refresh_interval
        self._last_refresh = 0.0
        self._refreshing = False
        self.lost = threading.Event()  # set when refresh quorum is lost

    def _quorum(self, writer: bool) -> int:
        n = len(self.lockers)
        tolerance = n // 2
        quorum = n - tolerance
        if writer and quorum == tolerance:
            quorum += 1  # ref drwmutex.go:130-138
        return quorum

    def _call_all(self, method: str, uid: str, pool=None) -> list[bool]:
        """One RPC per locker, CONCURRENTLY — a dead/partitioned peer
        must cost one RTT/timeout total, never a serial sum that stalls
        every acquisition behind it (the reference issues locker calls
        on goroutines). `pool` picks acquisition vs liveness workers."""
        if len(self.lockers) == 1:
            return [self.lockers[0].call(
                method, self.resource, uid, self.owner)]
        return list((pool or _lock_pool).map(
            lambda loc: loc.call(method, self.resource, uid, self.owner),
            self.lockers,
        ))

    def _acquire(self, writer: bool, timeout: float) -> bool:
        method = "lock" if writer else "rlock"
        quorum = self._quorum(writer)
        deadline = time.time() + timeout
        while True:
            uid = str(uuid.uuid4())
            granted = self._call_all(method, uid)
            if sum(granted) >= quorum:
                self.uid = uid
                self._writer = writer
                self._start_refresh()
                return True
            # roll back partial grants (ref releaseAll :504). A
            # rollback whose RPC fails at the transport leaks its grant
            # server-side until expiry exactly like a failed unlock —
            # count it the same way instead of dropping the error.
            rollback_failed = 0
            for i, ok in enumerate(granted):
                if ok:
                    _ok, err = self.lockers[i].call2(
                        "unlock", self.resource, uid, self.owner
                    )
                    if err is not None:
                        rollback_failed += 1
            if rollback_failed:
                _note_unlock_failures(rollback_failed, self.resource)
            if time.time() >= deadline:
                return False
            time.sleep(0.01 + 0.04 * (time.time() % 1))  # jittered retry

    def lock(self, timeout: float = 10.0) -> bool:
        return self._acquire(True, timeout)

    def rlock(self, timeout: float = 10.0) -> bool:
        return self._acquire(False, timeout)

    def unlock(self):
        self._stop_refresh_loop()
        # Dedicated pool: off the acquire pool (delayed unlocks extend
        # holds and feed acquisition storms) AND off the refresh pool
        # (an unlock storm against a dead peer must never starve the
        # refreshes keeping held locks alive).
        uid = self.uid
        outcomes = list(_unlock_pool.map(
            lambda loc: loc.call2("unlock", self.resource, uid, self.owner),
            self.lockers,
        )) if len(self.lockers) > 1 else [
            self.lockers[0].call2("unlock", self.resource, uid, self.owner)
        ]
        failed = sum(1 for _ok, err in outcomes if err is not None)
        if failed:
            # Each failed unlock RPC leaks its grant until server-side
            # expiry — export the count so leak storms are visible.
            _note_unlock_failures(failed, self.resource)
        self.uid = ""

    def force_unlock(self):
        self._stop_refresh_loop()
        failed = 0
        for loc in self.lockers:
            _ok, err = loc.call2("force_unlock", self.resource, "",
                                 self.owner)
            if err is not None:
                failed += 1
        if failed:
            # Same leak semantics as a failed unlock: the peer's entry
            # survives until server-side expiry.
            _note_unlock_failures(failed, self.resource)

    # --- refresh (ref drwmutex.go:214-345; executed by the shared
    # --- module ticker, never a per-acquisition thread) ---

    def _start_refresh(self):
        self.lost.clear()
        self._last_refresh = time.monotonic()
        self._refreshing = False
        _register_held(self)

    def _do_refresh(self):
        # Stamp at START: period must be start-to-start, or slow-but-
        # alive peers stretch the effective interval past the expiry
        # (dedup via _refreshing already prevents stacking).
        self._last_refresh = time.monotonic()
        try:
            uid = self.uid
            if not uid:
                return  # released between tick and execution
            # Serial per-locker calls: this runs ON the refresh pool,
            # and nested fan-out into the same pool could starve under
            # many held locks; a dead peer costs this mutex 5s, nobody
            # else's refresh.
            ok = sum(
                loc.call("refresh", self.resource, uid, self.owner)
                for loc in self.lockers
            )
            if self.uid == uid and ok < self._quorum(self._writer):
                # Lost the lock (e.g. lockers restarted / expired):
                # signal the owner to cancel its operation.
                self.lost.set()
                _deregister_held(self)
        finally:
            self._refreshing = False

    def _stop_refresh_loop(self):
        _deregister_held(self)


class Dsync:
    """Factory bundling the cluster's locker endpoints
    (ref pkg/dsync/dsync.go)."""

    def __init__(self, local: LocalLocker | None = None,
                 remote_endpoints: list[str] | None = None,
                 secret: str = ""):
        self.lockers: list[_LockerClient] = []
        if local is not None:
            self.lockers.append(_LockerClient(local=local))
        for ep in remote_endpoints or []:
            self.lockers.append(_LockerClient(endpoint=ep, secret=secret))

    def new_mutex(self, resource: str, owner: str = "",
                  refresh_interval: float = REFRESH_INTERVAL_S) -> DRWMutex:
        return DRWMutex(self.lockers, resource, owner, refresh_interval)
