"""Storage plane: every node serves its local disks to peers over RPC,
and RemoteStorage makes a peer disk look like a local StorageAPI —
behavioral parity with the reference's cmd/storage-rest-server.go /
cmd/storage-rest-client.go (34 StorageAPI methods over per-method
endpoints, msgpack args, streamed file bodies).
"""

from __future__ import annotations

import io

from ..observability import ioflow
from ..storage.fileinfo import FileInfo
from ..storage.interface import DiskInfo, FileInfoVersions, StorageAPI, VolInfo
from ..utils import errors as oe
from .rest import RPCClient, RPCError, RPCServer

STORAGE_PREFIX = "/mtpu/storage/v1"

# Typed errors cross the wire by class name (the reference ships error
# strings and rehydrates with toStorageErr, cmd/storage-rest-client.go).
_ERR_TYPES = {
    cls.__name__: cls
    for cls in vars(oe).values()
    if isinstance(cls, type) and issubclass(cls, Exception)
}


def _rehydrate(exc: RPCError) -> Exception:
    cls = _ERR_TYPES.get(exc.kind)
    if cls is not None:
        return cls(exc.message)
    if exc.kind == "Unreachable":
        return oe.ErrDiskNotFound(exc.message)
    return exc


def _fi_pack(fi: FileInfo) -> dict:
    return fi.to_dict()


# Methods safe to retry after a transient transport failure: pure reads
# and probes. Every mutating method stays out — an "Unreachable" on a
# write is AMBIGUOUS (the bytes may have landed before the reset), and
# replaying e.g. rename_data or delete_version could double-apply
# against a concurrent writer.
_IDEMPOTENT = frozenset({
    "ping", "disk_info", "get_disk_id", "list_vols", "stat_vol",
    "list_dir", "walk_dir", "read_version", "list_versions",
    "read_file", "read_file_stream", "read_repair_symbol", "read_all",
    "check_parts", "check_file", "verify_file", "stat_info_file",
})


class StorageRESTServer:
    """Expose a set of local disks at /mtpu/storage/v1/<method>?disk=N."""

    def __init__(self, disks: list, secret: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.disks = {d.endpoint(): d for d in disks}
        self.rpc = RPCServer(STORAGE_PREFIX, secret, host, port)
        for name in (
            "ping", "disk_info", "get_disk_id", "set_disk_id", "make_vol",
            "make_vol_bulk", "list_vols", "stat_vol", "delete_vol",
            "list_dir", "walk_dir", "delete_version", "delete_versions",
            "write_metadata", "update_metadata", "read_version",
            "rename_data", "list_versions", "read_file", "append_file",
            "create_file", "read_file_stream", "read_repair_symbol",
            "rename_file", "check_parts",
            "check_file", "delete", "verify_file", "write_all", "read_all",
            "stat_info_file",
        ):
            self.rpc.register(name, getattr(self, f"_h_{name}"))

    def start(self):
        self.rpc.start()
        return self

    def stop(self):
        self.rpc.stop()

    @property
    def endpoint(self) -> str:
        return self.rpc.endpoint

    def _disk(self, args: dict):
        d = self.disks.get(args.get("disk", ""))
        if d is None:
            raise oe.ErrDiskNotFound(args.get("disk", ""))
        return d

    # --- handlers ---

    def _h_ping(self, args, body):
        return {"ok": True}

    def _h_disk_info(self, args, body):
        di = self._disk(args).disk_info()
        return {
            "total": di.total, "free": di.free, "used": di.used,
            "fs_type": di.fs_type, "endpoint": di.endpoint,
            "mount_path": di.mount_path, "id": di.id, "error": di.error,
            "healing": di.healing,
        }

    def _h_get_disk_id(self, args, body):
        return {"id": self._disk(args).get_disk_id()}

    def _h_set_disk_id(self, args, body):
        self._disk(args).set_disk_id(args["id"])
        return {}

    def _h_make_vol(self, args, body):
        self._disk(args).make_vol(args["volume"])
        return {}

    def _h_make_vol_bulk(self, args, body):
        import msgpack

        self._disk(args).make_vol_bulk(*msgpack.unpackb(body, raw=False))
        return {}

    def _h_list_vols(self, args, body):
        return {
            "vols": [
                {"name": v.name, "created_ns": v.created_ns}
                for v in self._disk(args).list_vols()
            ]
        }

    def _h_stat_vol(self, args, body):
        v = self._disk(args).stat_vol(args["volume"])
        return {"name": v.name, "created_ns": v.created_ns}

    def _h_delete_vol(self, args, body):
        self._disk(args).delete_vol(
            args["volume"], args.get("force") == "1"
        )
        return {}

    def _h_list_dir(self, args, body):
        return {
            "entries": self._disk(args).list_dir(
                args["volume"], args.get("dir", ""),
                int(args.get("count", "-1")),
            )
        }

    def _h_walk_dir(self, args, body):
        entries = list(self._disk(args).walk_dir(
            args["volume"], args.get("base", ""),
            args.get("recursive", "1") == "1",
        ))
        return {"entries": entries}

    def _h_delete_version(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).delete_version(
            args["volume"], args["path"], fi,
            args.get("force_del_marker") == "1",
        )
        return {}

    def _h_delete_versions(self, args, body):
        import msgpack

        fis = [
            FileInfo.from_dict(d)
            for d in msgpack.unpackb(body, raw=False)
        ]
        errs = self._disk(args).delete_versions(args["volume"], fis)
        return {
            "errors": [
                None if e is None else {
                    "kind": type(e).__name__, "message": str(e)
                }
                for e in errs
            ]
        }

    def _h_write_metadata(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).write_metadata(args["volume"], args["path"], fi)
        return {}

    def _h_update_metadata(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).update_metadata(args["volume"], args["path"], fi)
        return {}

    def _h_read_version(self, args, body):
        fi = self._disk(args).read_version(
            args["volume"], args["path"], args.get("version_id", ""),
            args.get("read_data") == "1",
        )
        return _fi_pack(fi)

    def _h_rename_data(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).rename_data(
            args["src_volume"], args["src_path"], fi,
            args["dst_volume"], args["dst_path"],
        )
        return {}

    def _h_list_versions(self, args, body):
        fv = self._disk(args).list_versions(args["volume"], args["path"])
        return {
            "volume": fv.volume, "name": fv.name,
            "versions": [_fi_pack(f) for f in fv.versions],
        }

    def _h_read_file(self, args, body):
        data = self._disk(args).read_file(
            args["volume"], args["path"],
            int(args["offset"]), int(args["length"]),
        )
        return {"n": len(data)}, io.BytesIO(data)

    def _h_append_file(self, args, body):
        self._disk(args).append_file(args["volume"], args["path"], body)
        return {}

    def _h_create_file(self, args, body):
        self._disk(args).create_file(
            args["volume"], args["path"], int(args["size"]),
            io.BytesIO(body),
        )
        return {}

    def _h_read_file_stream(self, args, body):
        stream = self._disk(args).read_file_stream(
            args["volume"], args["path"],
            int(args["offset"]), int(args["length"]),
        )
        try:
            data = stream.read()
        finally:
            close = getattr(stream, "close", None)
            if close:
                close()
        return {"n": len(data)}, io.BytesIO(data)

    def _h_read_repair_symbol(self, args, body):
        # β-slice read for the repair plane (erasure/repair.py): subs is
        # a CSV of sub-shard indices, blocks a CSV of block:chunk_len
        # pairs. Only the requested β bytes come back — the wire-cost
        # contract that makes remote regenerating repair cheaper than
        # shipping whole shards. Op attribution (heal) rides the
        # forwarded _IOFLOW_OP_HDR like every other storage RPC.
        data = self._disk(args).read_repair_symbol(
            args["volume"], args["path"],
            stride=int(args["stride"]),
            digest_size=int(args["digest_size"]),
            alpha=int(args["alpha"]),
            subs=[int(s) for s in args["subs"].split(",")],
            blocks=[
                (int(b), int(c))
                for b, c in (p.split(":") for p in args["blocks"].split(","))
            ],
        )
        return {"n": len(data)}, io.BytesIO(data)

    def _h_rename_file(self, args, body):
        self._disk(args).rename_file(
            args["src_volume"], args["src_path"],
            args["dst_volume"], args["dst_path"],
        )
        return {}

    def _h_check_parts(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).check_parts(args["volume"], args["path"], fi)
        return {}

    def _h_check_file(self, args, body):
        self._disk(args).check_file(args["volume"], args["path"])
        return {}

    def _h_delete(self, args, body):
        self._disk(args).delete(
            args["volume"], args["path"], args.get("recursive") == "1"
        )
        return {}

    def _h_verify_file(self, args, body):
        import msgpack

        fi = FileInfo.from_dict(msgpack.unpackb(body, raw=False))
        self._disk(args).verify_file(args["volume"], args["path"], fi)
        return {}

    def _h_write_all(self, args, body):
        self._disk(args).write_all(args["volume"], args["path"], body)
        return {}

    def _h_read_all(self, args, body):
        data = self._disk(args).read_all(args["volume"], args["path"])
        return {"n": len(data)}, io.BytesIO(data)

    def _h_stat_info_file(self, args, body):
        st = self._disk(args).stat_info_file(args["volume"], args["path"])
        return {"size": st.st_size, "mod_time_ns": st.st_mtime_ns}


class _RemoteStat:
    """os.stat_result analog for remote files (size + mtime are what the
    object layer consumes; ref StatInfoFile returns StatInfo{Size,ModTime},
    cmd/storage-rest-client.go). Mtime crosses the wire in nanoseconds —
    the repo-wide convention (st_mtime_ns everywhere, e.g. object/fs.py)."""

    __slots__ = ("st_size", "st_mtime_ns")

    def __init__(self, size: int, mtime_ns: int):
        self.st_size = size
        self.st_mtime_ns = mtime_ns

    @property
    def st_mtime(self) -> float:
        return self.st_mtime_ns / 1e9


class _RemoteWriter:
    """Buffering writable sink for create_file_writer over the wire. The
    reference streams via io.Pipe into CreateFile's request body
    (cmd/bitrot-streaming.go:89-97); shard files are ≤ a few MiB per part
    so a buffered single POST keeps the wire protocol simple."""

    def __init__(self, client: "RemoteStorage", volume: str, path: str):
        self._c = client
        self._volume = volume
        self._path = path
        self._buf = bytearray()
        self.closed = False

    def write(self, data) -> int:
        self._buf += bytes(data)
        return len(data)

    def close(self):
        if self.closed:
            return
        self.closed = True
        self._c.create_file(
            self._volume, self._path, len(self._buf), io.BytesIO(bytes(self._buf))
        )


class RemoteStorage(StorageAPI):
    """StorageAPI over the storage REST plane (one peer disk)."""

    def __init__(self, node_endpoint: str, disk_endpoint: str, secret: str,
                 timeout: float = 30.0):
        self._node = node_endpoint
        self._disk_ep = disk_endpoint
        self._client = RPCClient(
            node_endpoint, STORAGE_PREFIX, secret, timeout
        )

    def _call(self, method: str, args: dict | None = None,
              body: bytes = b"", want_stream: bool = False):
        a = {"disk": self._disk_ep}
        a.update(args or {})
        try:
            return self._client.call(method, a, body, want_stream,
                                     idempotent=method in _IDEMPOTENT)
        except RPCError as exc:
            raise _rehydrate(exc) from exc

    # --- identity ---

    def ping(self) -> None:
        """Round-trip liveness probe over the REST plane (the reference's
        storage client health check)."""
        self._call("ping")

    def is_online(self) -> bool:
        return self._client.online

    def is_local(self) -> bool:
        return False

    def hostname(self) -> str:
        return self._node

    def endpoint(self) -> str:
        return f"{self._node}/{self._disk_ep}"

    def get_disk_id(self) -> str:
        return self._call("get_disk_id")["id"]

    def set_disk_id(self, disk_id: str) -> None:
        self._call("set_disk_id", {"id": disk_id})

    def disk_info(self) -> DiskInfo:
        d = self._call("disk_info")
        return DiskInfo(
            total=d["total"], free=d["free"], used=d["used"],
            fs_type=d["fs_type"], endpoint=self.endpoint(),
            mount_path=d["mount_path"], id=d["id"], error=d["error"],
            healing=d["healing"],
        )

    # --- volumes ---

    def make_vol(self, volume: str) -> None:
        self._call("make_vol", {"volume": volume})

    def make_vol_bulk(self, *volumes: str) -> None:
        import msgpack

        self._call("make_vol_bulk", body=msgpack.packb(list(volumes)))

    def list_vols(self) -> list[VolInfo]:
        return [
            VolInfo(v["name"], v["created_ns"])
            for v in self._call("list_vols")["vols"]
        ]

    def stat_vol(self, volume: str) -> VolInfo:
        v = self._call("stat_vol", {"volume": volume})
        return VolInfo(v["name"], v["created_ns"])

    def delete_vol(self, volume: str, force_delete: bool = False) -> None:
        self._call("delete_vol", {
            "volume": volume, "force": "1" if force_delete else "0",
        })

    # --- listing ---

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        return self._call("list_dir", {
            "volume": volume, "dir": dir_path, "count": str(count),
        })["entries"]

    def walk_dir(self, volume: str, base_dir: str = "", recursive: bool = True,
                 report_notfound: bool = False, forward_to: str = ""):
        for e in self._call("walk_dir", {
            "volume": volume, "base": base_dir,
            "recursive": "1" if recursive else "0",
        })["entries"]:
            yield tuple(e)  # msgpack turns (path, meta_bytes) into a list

    # --- metadata ---

    def delete_version(self, volume: str, path: str, fi: FileInfo,
                       force_del_marker: bool = False) -> None:
        import msgpack

        self._call("delete_version", {
            "volume": volume, "path": path,
            "force_del_marker": "1" if force_del_marker else "0",
        }, msgpack.packb(_fi_pack(fi), use_bin_type=True))

    def delete_versions(self, volume: str, versions: list[FileInfo]) -> list:
        import msgpack

        res = self._call(
            "delete_versions", {"volume": volume},
            msgpack.packb([_fi_pack(f) for f in versions], use_bin_type=True),
        )
        out = []
        for e in res["errors"]:
            if e is None:
                out.append(None)
            else:
                cls = _ERR_TYPES.get(e["kind"], oe.StorageError)
                out.append(cls(e["message"]))
        return out

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        import msgpack

        self._call("write_metadata", {"volume": volume, "path": path},
                   msgpack.packb(_fi_pack(fi), use_bin_type=True))

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        import msgpack

        self._call("update_metadata", {"volume": volume, "path": path},
                   msgpack.packb(_fi_pack(fi), use_bin_type=True))

    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo:
        d = self._call("read_version", {
            "volume": volume, "path": path, "version_id": version_id,
            "read_data": "1" if read_data else "0",
        })
        return FileInfo.from_dict(d)

    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        import msgpack

        self._call("rename_data", {
            "src_volume": src_volume, "src_path": src_path,
            "dst_volume": dst_volume, "dst_path": dst_path,
        }, msgpack.packb(_fi_pack(fi), use_bin_type=True))

    # --- files ---

    def list_versions(self, volume: str, path: str) -> FileInfoVersions:
        d = self._call("list_versions", {"volume": volume, "path": path})
        return FileInfoVersions(
            volume=d["volume"], name=d["name"],
            versions=[FileInfo.from_dict(v) for v in d["versions"]],
        )

    def read_file(self, volume: str, path: str, offset: int,
                  length: int) -> bytes:
        _, data = self._call("read_file", {
            "volume": volume, "path": path,
            "offset": str(offset), "length": str(length),
        }, want_stream=True)
        return data

    def append_file(self, volume: str, path: str, buf: bytes) -> None:
        self._call("append_file", {"volume": volume, "path": path}, bytes(buf))

    def create_file(self, volume: str, path: str, size: int, reader) -> None:
        data = reader.read() if hasattr(reader, "read") else bytes(reader)
        self._call("create_file", {
            "volume": volume, "path": path, "size": str(size),
        }, data)

    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int):
        _, data = self._call("read_file_stream", {
            "volume": volume, "path": path,
            "offset": str(offset), "length": str(length),
        }, want_stream=True)
        return io.BytesIO(data)

    def read_repair_symbol(self, volume: str, path: str, *, stride: int,
                           digest_size: int, alpha: int, subs: list[int],
                           blocks: list[tuple[int, int]]) -> bytes:
        """One RPC per call: the whole β-slice request for this survivor
        crosses the wire as a single round trip and only the β bytes come
        back. The serving node ledgers its own disk read; this side
        accounts the received bytes under the "rwire" direction so
        repair_wire_bytes_per_byte_healed can prove wire ≈ d·β, not
        d·shard."""
        _, data = self._call("read_repair_symbol", {
            "volume": volume, "path": path,
            "stride": str(stride), "digest_size": str(digest_size),
            "alpha": str(alpha),
            "subs": ",".join(str(s) for s in subs),
            "blocks": ",".join(f"{b}:{c}" for b, c in blocks),
        }, want_stream=True)
        ioflow.account(self.endpoint(), "rwire", len(data))
        return data

    def create_file_writer(self, volume: str, path: str,
                           size: int = -1):
        # The size hint is not forwarded here: the buffered writer knows
        # the EXACT length at close and ships it on the CreateFile RPC,
        # where the server-side LocalStorage.create_file applies the
        # O_DIRECT/fallocate treatment.
        return _RemoteWriter(self, volume, path)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        self._call("rename_file", {
            "src_volume": src_volume, "src_path": src_path,
            "dst_volume": dst_volume, "dst_path": dst_path,
        })

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        import msgpack

        self._call("check_parts", {"volume": volume, "path": path},
                   msgpack.packb(_fi_pack(fi), use_bin_type=True))

    def check_file(self, volume: str, path: str) -> None:
        self._call("check_file", {"volume": volume, "path": path})

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call("delete", {
            "volume": volume, "path": path,
            "recursive": "1" if recursive else "0",
        })

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        import msgpack

        self._call("verify_file", {"volume": volume, "path": path},
                   msgpack.packb(_fi_pack(fi), use_bin_type=True))

    def stat_info_file(self, volume: str, path: str):
        d = self._call("stat_info_file", {"volume": volume, "path": path})
        return _RemoteStat(d["size"], d["mod_time_ns"])

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("write_all", {"volume": volume, "path": path}, bytes(data))

    def read_all(self, volume: str, path: str) -> bytes:
        _, data = self._call("read_all", {"volume": volume, "path": path},
                             want_stream=True)
        return data
