"""Generic node-to-node RPC: authed POST endpoints with msgpack bodies,
connection pooling, health checking — the equivalent of the reference's
cmd/rest/client.go (bearer-JWT authed per-method POSTs) re-designed on
Python http primitives with HMAC tokens.

All three distributed planes (storage, lock, peer-control) ride on this.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import logging
import random
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import msgpack

from ..observability import ioflow

TOKEN_VALIDITY_S = 15 * 60

# --- transient-failure retry (idempotent methods only) ---------------------
# A 1s network blip (peer restart, conntrack flush) must not fail an
# in-flight GET whose shard read would succeed 100ms later. One
# jittered-backoff retry, only when the CALLER declared the method
# idempotent (reads/probes; a write retried after an ambiguous failure
# could apply twice), and only within the call's original deadline.
RETRY_MIN_BUDGET_S = 0.05
RETRY_BACKOFF_S = (0.02, 0.15)

RPC_DESCRIPTORS: list[tuple[str, str, str]] = [
    ("rpc_retries_total", "counter",
     "Idempotent RPC calls retried after a transient transport failure"),
]

_metrics = None  # guarded-by: _metrics_mu
_metrics_mu = threading.Lock()
# Process totals, importable by tests/bench without a registry.
RETRIES = {"total": 0}  # guarded-by: _metrics_mu


def set_metrics(registry) -> None:
    global _metrics
    with _metrics_mu:
        _metrics = registry


def _note_retry() -> None:
    with _metrics_mu:
        RETRIES["total"] += 1
        reg = _metrics
    if reg is not None:
        reg.inc("rpc_retries_total")

# The byte-flow op tag crosses the wire in these headers so the node
# that OWNS the disk attributes its own syscall-layer bytes to the
# originating request's op-class — the proxy never counts remote bytes
# (each byte lands in exactly one node's ledger, correctly classified).
_IOFLOW_OP_HDR = "X-Mtpu-Ioflow-Op"
_IOFLOW_BUCKET_HDR = "X-Mtpu-Ioflow-Bucket"

_log = logging.getLogger("minio_tpu.rpc")


class RPCError(Exception):
    """Remote call failed; carries the remote error type name for
    re-raising typed storage errors client-side."""

    def __init__(self, kind: str, message: str):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message


def make_token(secret: str, now: float | None = None) -> str:
    """HMAC cluster token: base64(payload).hexsig (the reference uses
    JWT with the root credential as signing key, cmd/rest/client.go:128)."""
    payload = json.dumps({
        "exp": (now or time.time()) + TOKEN_VALIDITY_S,
    }).encode()
    b64 = base64.urlsafe_b64encode(payload).decode()
    sig = hmac.new(secret.encode(), b64.encode(), hashlib.sha256).hexdigest()
    return f"{b64}.{sig}"


def verify_token(secret: str, token: str) -> bool:
    try:
        b64, sig = token.split(".", 1)
    except ValueError:
        return False
    want = hmac.new(secret.encode(), b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, sig):
        return False
    try:
        payload = json.loads(base64.urlsafe_b64decode(b64))
    # except-ok: malformed credential classifies as invalid token; the False IS the outcome
    except Exception:
        return False
    return payload.get("exp", 0) > time.time()


class RPCServer:
    """HTTP server exposing named methods under a version prefix.

    Handlers: fn(args: dict, body: bytes) -> (result, stream) where
    result is msgpack-encoded and stream (optional file-like) is sent as
    the raw response body after the msgpack frame length header.
    """

    def __init__(self, prefix: str, secret: str, host: str = "127.0.0.1",
                 port: int = 0, tls=None):
        from ..utils import certs as _certs

        self.prefix = prefix.rstrip("/")
        self.secret = secret
        # TLS: explicit manager, else the process-global one (set at
        # server boot) so every RPC plane upgrades together — bearer
        # secrets must never cross the wire in the clear when the
        # deployment has certs (ref cmd/server-main.go:431-433).
        self.tls = tls if tls is not None else _certs.global_tls()
        self._methods: dict = {}
        # Live connection sockets, so stop() can sever keep-alive peers —
        # shutdown() alone leaves pooled client connections being served
        # by their handler threads, which is not what "node died" means.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def setup(self):
                super().setup()
                with outer._conns_lock:
                    outer._conns.add(self.connection)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.connection)
                super().finish()

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                outer._handle(self)

        class _Server(ThreadingHTTPServer):
            def finish_request(self, request, client_address):
                # Per-connection TLS wrap in the HANDLER thread: wrapping
                # the listening socket would run handshakes in the accept
                # loop, letting one slow client stall every plane peer.
                if outer.tls is not None:
                    request = outer.tls.server_context.wrap_socket(
                        request, server_side=True
                    )
                super().finish_request(request, client_address)

            def handle_error(self, request, client_address):
                import ssl as _ssl
                import sys as _sys

                # Client resets/disconnects during node outages are
                # routine — never spray tracebacks to stderr for them;
                # ditto handshake failures from port scanners /
                # plaintext probes of a TLS plane.
                exc = _sys.exc_info()[1]
                if isinstance(exc, (ConnectionResetError,
                                    BrokenPipeError, TimeoutError,
                                    _ssl.SSLError)):
                    return
                super().handle_error(request, client_address)

        self.httpd = _Server((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: threading.Thread | None = None

    def register(self, name: str, fn):
        self._methods[name] = fn

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        import socket as _socket

        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def _handle(self, h: BaseHTTPRequestHandler):
        parsed = urllib.parse.urlsplit(h.path)
        if not parsed.path.startswith(self.prefix + "/"):
            self._reply_error(h, 404, "NotFound", parsed.path)
            return
        token = h.headers.get("Authorization", "").removeprefix("Bearer ")
        if not verify_token(self.secret, token):
            self._reply_error(h, 403, "AccessDenied", "bad cluster token")
            return
        method = parsed.path[len(self.prefix) + 1:]
        fn = self._methods.get(method)
        if fn is None:
            self._reply_error(h, 404, "UnknownMethod", method)
            return
        args = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        clen = int(h.headers.get("Content-Length", "0") or "0")
        body = h.rfile.read(clen) if clen else b""
        # Dispatch under the caller's byte-flow op tag (token already
        # verified above, and unknown classes are dropped) so local
        # disk IO this call triggers is attributed, not "untagged".
        op = h.headers.get(_IOFLOW_OP_HDR, "")
        if op not in ioflow.OP_CLASSES:
            op = ""
        try:
            if op:
                with ioflow.tag(op, h.headers.get(_IOFLOW_BUCKET_HDR, "")):
                    out = fn(args, body)
            else:
                out = fn(args, body)
        except Exception as exc:  # noqa: BLE001 - typed error to client
            self._reply_error(h, 500, type(exc).__name__, str(exc))
            return
        result, stream = out if isinstance(out, tuple) else (out, None)
        frame = msgpack.packb(result, use_bin_type=True)
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/x-msgpack")
            h.send_header("X-Frame-Length", str(len(frame)))
            if stream is None:
                h.send_header("Content-Length", str(len(frame)))
                h.end_headers()
                h.wfile.write(frame)
            else:
                data = stream.read() if hasattr(stream, "read") else bytes(stream)
                h.send_header("Content-Length", str(len(frame) + len(data)))
                h.end_headers()
                h.wfile.write(frame)
                h.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _reply_error(self, h, status: int, kind: str, message: str):
        try:
            body = msgpack.packb(
                {"__error__": kind, "message": message}, use_bin_type=True
            )
            h.send_response(status)
            h.send_header("Content-Type", "application/x-msgpack")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


class RPCClient:
    """Pooled, health-checked client for one peer's RPC plane
    (ref cmd/rest/client.go:120-188 Call + health check loop)."""

    def __init__(self, endpoint: str, prefix: str, secret: str,
                 timeout: float = 30.0):
        self.endpoint_str = endpoint
        self.prefix = prefix.rstrip("/")
        self.secret = secret
        self.timeout = timeout
        self._online = True
        self._last_check = 0.0
        self._lock = threading.Lock()
        self._pool: list[http.client.HTTPConnection] = []
        # Serializes the lazy reconnect probe: without it, racing
        # threads reading .online double-probe the peer and clobber
        # _last_check (losing the 1s backoff).
        self._probe_lock = threading.Lock()
        # "" | "net: ..." | "auth: ..." — the last probe's failure
        # class, so an auth problem (clock skew, secret mismatch) is
        # distinguishable from a plain network outage.
        self.last_probe_error = ""

    # --- connection pool ---

    def _new_conn(self, timeout_s: float) -> http.client.HTTPConnection:
        from ..utils import certs as _certs

        ctx = _certs.client_ssl_context()
        if ctx is not None:
            return http.client.HTTPSConnection(
                self.endpoint_str, timeout=timeout_s, context=ctx
            )
        return http.client.HTTPConnection(
            self.endpoint_str, timeout=timeout_s
        )

    def _get_conn(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return self._new_conn(self.timeout)

    def _put_conn(self, conn):
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(conn)
                return
        conn.close()

    # --- health ---

    @property
    def online(self) -> bool:
        if self._online:
            return True
        if time.time() - self._last_check <= 1.0:
            return False
        # Lazy reconnect probe (ref: HealthCheckFn + 1s backoff). The
        # probe is network I/O inside a property getter, so it MUST be
        # single-flight: one thread probes, the others return the
        # current state instead of stacking probes and clobbering the
        # backoff stamp.
        # lock-ok: non-blocking single-flight probe gate; released in
        # the finally below, never held across a wait
        if not self._probe_lock.acquire(blocking=False):
            return self._online
        try:
            if self._online or time.time() - self._last_check <= 1.0:
                return self._online
            self._last_check = time.time()
            try:
                self.call("ping")
                self._online = True
                self.last_probe_error = ""
            except RPCError as exc:
                if exc.kind == "AccessDenied":
                    # The peer IS reachable but rejects our cluster
                    # token (secret mismatch / clock skew past token
                    # validity). Reporting this as a plain "offline"
                    # sends operators chasing the network; log the real
                    # cause once per transition.
                    if not self.last_probe_error.startswith("auth"):
                        _log.warning(
                            "peer %s rejects cluster token (%s): check "
                            "shared secret / clock skew, not the network",
                            self.endpoint_str, exc.message,
                        )
                    self.last_probe_error = f"auth: {exc.message}"
                else:
                    self.last_probe_error = f"net: {exc.message}"
            except Exception as exc:  # noqa: BLE001 - probe best effort
                self.last_probe_error = f"net: {exc}"
        finally:
            self._probe_lock.release()
        return self._online

    def mark_offline(self):
        self._online = False
        self._last_check = time.time()

    # --- calls ---

    def call(self, method: str, args: dict | None = None,
             body: bytes = b"", want_stream: bool = False,
             idempotent: bool = False):
        """POST one method. Returns the msgpack result, or
        (result, raw_rest_of_body) when want_stream.

        `idempotent=True` (reads/probes only — never a write, whose
        ambiguous first attempt may have applied) grants ONE
        jittered-backoff retry after a transient transport failure
        (connect reset/refused/timeout), inside the call's ORIGINAL
        deadline: the retry's connection timeout is the remaining
        budget, so a caller that asked for `timeout` seconds never
        waits longer because a blip happened."""
        deadline = time.monotonic() + self.timeout
        try:
            return self._call_once(method, args, body, want_stream)
        except RPCError as exc:
            if not idempotent or exc.kind != "Unreachable":
                raise
            remaining = deadline - time.monotonic()
            if remaining <= RETRY_MIN_BUDGET_S:
                raise  # no budget left: surface the first failure
            time.sleep(min(random.uniform(*RETRY_BACKOFF_S),
                           remaining / 4))
            remaining = deadline - time.monotonic()
            if remaining <= RETRY_MIN_BUDGET_S:
                raise
            _note_retry()
            out = self._call_once(method, args, body, want_stream,
                                  timeout_s=remaining)
            # The retry round-tripped: the peer is back. Re-admit it
            # immediately instead of waiting out the probe backoff.
            self._online = True
            self.last_probe_error = ""
            return out

    def _call_once(self, method: str, args: dict | None,
                   body: bytes, want_stream: bool,
                   timeout_s: float | None = None):
        qs = urllib.parse.urlencode(args or {})
        url = f"{self.prefix}/{method}" + (f"?{qs}" if qs else "")
        headers = {
            "Authorization": f"Bearer {make_token(self.secret)}",
            "Content-Length": str(len(body)),
        }
        tag = ioflow.capture()
        if tag is not None:
            headers[_IOFLOW_OP_HDR] = tag.op
            if tag.bucket:
                headers[_IOFLOW_BUCKET_HDR] = tag.bucket
        # A deadline-propagated retry never draws from the pool: pooled
        # sockets carry the full default timeout, and a dead keep-alive
        # from before the blip would burn the remaining budget twice.
        conn = (self._get_conn() if timeout_s is None
                else self._new_conn(timeout_s))
        try:
            conn.request("POST", url, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            if timeout_s is None:
                self._put_conn(conn)
            else:
                # Never pool the retry's short-timeout socket: a later
                # unrelated call inheriting the truncated budget would
                # time out spuriously and latch the peer offline.
                conn.close()
        except (OSError, http.client.HTTPException) as exc:
            conn.close()
            self.mark_offline()
            raise RPCError("Unreachable", str(exc)) from exc
        frame_len = int(resp.headers.get("X-Frame-Length", len(raw)))
        result = msgpack.unpackb(raw[:frame_len], raw=False)
        if isinstance(result, dict) and "__error__" in result:
            raise RPCError(result["__error__"], result.get("message", ""))
        if want_stream:
            return result, raw[frame_len:]
        return result
