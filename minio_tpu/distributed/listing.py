"""Cross-node metacache listing coordination — the distributed analog of
the reference's peer-managed metacache (cmd/metacache-server-pool.go:59,
cmd/metacache-bucket.go, peerRESTMethodGetMetacacheListing /
UpdateMetacacheListing).

The reference makes one node the manager of each bucket's listings so
that concurrent ListObjects calls from different nodes share ONE
resumable walk instead of each walking every disk. Here the same idea,
re-shaped for this runtime's generation-based caches:

- Each (bucket, prefix) listing has a deterministic OWNER node (hash of
  the listing path over the sorted node set — sipHashMod's role for
  objects, applied to listings).
- A page request on a non-owner node is proxied to the owner over the
  peer control plane (`list_page`), so the owner's ListingCache serves
  every node's pages and each disk is still walked only once per
  generation, cluster-wide.
- If the owner is unreachable the node serves the page from its own
  local cache (availability over shared-walk efficiency — same
  degradation the reference takes when the cache owner is down).
- Mutations anywhere broadcast a batched `bump_listing_gen` to peers so
  every node's generation counter moves and stale caches die at the
  next page (the reference leans on bloom-filter cycles + time windows;
  a 50 ms batch window gives cross-node read-your-writes instead).
"""

from __future__ import annotations

import threading
import zlib

from .rest import RPCError

BATCH_WINDOW_S = 0.05


class ListingCoordinator:
    """Routes metacache page requests to the listing's owner node and
    propagates mutation-driven generation bumps to peers."""

    def __init__(self, object_layer, self_endpoint: str, peers: dict):
        """peers: {endpoint: PeerClient} for every OTHER node."""
        self.ol = object_layer
        self.self_endpoint = self_endpoint
        self.peers = dict(peers)
        self._nodes = sorted([self_endpoint, *peers])
        # stats (exported for tests/metrics)
        self.local_pages = 0
        self.remote_pages = 0
        self.fallback_pages = 0
        # mutation broadcast batcher
        self._dirty: set[str] = set()
        self._dirty_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._broadcast_loop, daemon=True, name="mtpu-listgen"
        )
        self._thread.start()

    # --- ownership ---

    def owner_of(self, bucket: str, prefix: str) -> str:
        h = zlib.crc32(f"{bucket}/{prefix}".encode())
        return self._nodes[h % len(self._nodes)]

    # --- paging ---

    def page(self, bucket: str, prefix: str, gen: int, marker: str,
             count: int, stream_factory):
        owner = self.owner_of(bucket, prefix)
        if owner == self.self_endpoint:
            self.local_pages += 1
            return self.ol._metacache.page(
                bucket, prefix, gen, marker, count, stream_factory
            )
        peer = self.peers[owner]
        try:
            # The caller's generation rides along so the owner's view is
            # at least as fresh as the caller's — without it, a write on
            # this node followed by an immediate list could be served
            # from an owner cache built before the write (the 50 ms bump
            # broadcast may not have landed yet).
            res = peer.call("list_page", {
                "bucket": bucket, "prefix": prefix,
                "marker": marker, "count": str(count), "gen": str(gen),
            })
            self.remote_pages += 1
            return (
                [(n, b) for n, b in res["entries"]],
                bool(res["exhausted"]),
            )
        except RPCError:
            # Owner down: serve from the local cache (reference behavior:
            # fall back to a locally-managed listing).
            self.fallback_pages += 1
            return self.ol._metacache.page(
                bucket, prefix, gen, marker, count, stream_factory
            )

    # --- mutation propagation ---

    def notify_mutation(self, bucket: str):
        """Called by the object layer on every listing-invalidating
        write; batched into one broadcast per window."""
        with self._dirty_lock:
            self._dirty.add(bucket)
        self._wake.set()

    def flush(self):
        """Synchronously broadcast pending bumps (tests/shutdown)."""
        self._drain()

    def _drain(self):
        with self._dirty_lock:
            dirty, self._dirty = self._dirty, set()
        for bucket in dirty:
            for peer in self.peers.values():
                try:
                    peer.call("bump_listing_gen", {"bucket": bucket})
                except RPCError:
                    continue  # peer will rebuild its cache on reconnect

    def _broadcast_loop(self):
        while not self._stop.is_set():
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            self._stop.wait(BATCH_WINDOW_S)  # batch window
            self._drain()

    def close(self):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2)
        self._drain()
