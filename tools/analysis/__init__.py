"""Project static-analysis plane: the machine-checked discipline behind
the erasure hot path (see docs/ANALYSIS.md).

Six per-statement AST lint rules encode the invariants PRs 2-9
enforced by hand — zero-copy accounting (copy-lint), no blocking work
under a threading.Lock (lock-lint), buffer-pool checkout/release
pairing on every path (pool-lint), jit dispatch hygiene (jax-lint),
no silently swallowed errors on quorum/delivery paths (except-lint),
and metrics series named in a descriptor catalog (metrics-lint).

Four dataflow rules (ISSUE 13) interpret whole functions through
``dataflow.py``'s abstract-interpretation engine — pooled-buffer
lifetime verification (lifetime-lint), the worker plane's
zero-payload-over-pipe invariant (shm-lint), ``# guarded-by:`` lock
annotations verified at every access (guardedby-lint), and MTPU_*
env-knob documentation/defaults (knob-lint) — plus a runtime
lock-order checker (lockgraph) armed in the concurrency stress
suites.

Tier-1 gate: tests/test_static_analysis.py runs the full scan and
fails on any finding not pinned in tools/analysis/baseline.json.
CLI: ``python -m tools.analysis`` emits the JSON report and exits
non-zero on new findings; ``--rule``, ``--since``, ``--jobs`` scope
and parallelize local iteration.
"""

from .engine import Finding, load_baseline, run, write_baseline

__all__ = ["Finding", "run", "load_baseline", "write_baseline"]
