"""Project static-analysis plane: the machine-checked discipline behind
the erasure hot path (see docs/ANALYSIS.md).

Five AST lint rules encode the invariants PRs 2-4 enforced by hand —
zero-copy accounting (copy-lint), no blocking work under a
threading.Lock (lock-lint), buffer-pool checkout/release pairing on
every path (pool-lint), jit dispatch hygiene (jax-lint), and no
silently swallowed errors on quorum/delivery paths (except-lint) —
plus a runtime lock-order checker (lockgraph) armed in the
concurrency stress suites.

Tier-1 gate: tests/test_static_analysis.py runs the full scan and
fails on any finding not pinned in tools/analysis/baseline.json.
CLI: ``python -m tools.analysis`` emits the JSON report and exits
non-zero on new findings.
"""

from .engine import Finding, load_baseline, run, write_baseline

__all__ = ["Finding", "run", "load_baseline", "write_baseline"]
