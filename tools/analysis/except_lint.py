"""except-lint: swallowed errors on quorum and delivery paths.

Scope: ``utils/fanout.py``, ``distributed/``, ``event/`` (the fan-out
and notification delivery planes, where a silently dropped error is a
quorum miscount or an invisible outage) plus ``tools/analysis/`` so
the analyzer holds itself to the rule.

Flags a bare ``except:`` or broad ``except Exception/BaseException:``
whose handler *drops* the error — no re-raise, no use of the bound
exception, and no recording call (logging, metrics ``inc``, counter
``record``/``note``/``add``). ``pass``-bodies on a quorum-relevant
failure are exactly the bug class this exists for. Waive deliberate
best-effort sites with ``# except-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .engine import Finding

KEY = "except"

SCOPES = (
    "minio_tpu/utils/fanout.py",
    "minio_tpu/distributed/",
    "minio_tpu/event/",
    "tools/analysis/",
    # Added since PR6 (ISSUE 13): the whole concurrency plane plus the
    # span/mesh planes — a swallowed error there is a silently-dead
    # worker, a leaked admission slot, or an invisible trace loss.
    "minio_tpu/pipeline/",
    "minio_tpu/observability/spans.py",
    "minio_tpu/parallel/mesh_engine.py",
    # Added with ISSUE 15: the fault/scenario plane — a scenario engine
    # that silently drops an op failure reports a soak as green that
    # was not, and the injector's own swallowed errors hide armed
    # faults from the drill they were meant to drive.
    "minio_tpu/faults/",
)

_BROAD = {"Exception", "BaseException"}
_RECORD_HINTS = (
    "log", "warn", "error", "exception", "inc", "record", "note",
    "metric", "count", "add", "print", "append",
)


class ExceptLint:
    name = "except-lint"

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        return rel.startswith(SCOPES) or rel in SCOPES

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue
            if self._handler_records(node):
                continue
            yield Finding(
                rule=self.name, path=ctx.relpath, line=node.lineno,
                col=node.col_offset, scope=ctx.scope_of(node),
                message=(
                    "broad except drops the error — count it, log it, "
                    "or re-raise (never 'pass' a quorum-relevant "
                    "failure); waive with '# except-ok: <reason>'"
                ),
                snippet=ctx.line_text(node.lineno),
            )

    def _is_broad(self, node: ast.ExceptHandler) -> bool:
        if node.type is None:
            return True  # bare except
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            if astutil.dotted_name(t).rsplit(".", 1)[-1] in _BROAD:
                return True
        return False

    def _handler_records(self, node: ast.ExceptHandler) -> bool:
        # Re-raise anywhere in the handler keeps the error alive.
        for sub in ast.walk(ast.Module(body=list(node.body),
                                       type_ignores=[])):
            if isinstance(sub, ast.Raise):
                return True
            # A counter latch (`FAILS["n"] += 1`, `self.drops += 1`)
            # records the failure even without touching the exception.
            if isinstance(sub, ast.AugAssign):
                return True
            # The bound exception being USED (assigned somewhere,
            # appended, passed along) means it is not dropped.
            if node.name and isinstance(sub, ast.Name) \
                    and sub.id == node.name:
                return True
            if isinstance(sub, ast.Call):
                leaf = astutil.call_name(sub).lower()
                if any(h in leaf for h in _RECORD_HINTS):
                    return True
        return False


RULE = ExceptLint()
