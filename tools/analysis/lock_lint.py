"""lock-lint: blocking work while holding a threading.Lock/RLock, and
locks acquired outside ``with``.

A blocking call under a lock turns one slow peer/disk into process-wide
convoy — the bug class PR 2's hung-drive work existed to kill. The rule
identifies lock objects structurally (names/attributes assigned from
``threading.Lock()`` / ``threading.RLock()`` anywhere in the module;
Conditions are excluded — waiting under one is their purpose) and flags,
inside ``with <lock>:`` bodies:

- ``time.sleep`` / bare ``sleep``
- ``Future.result()`` and ``.wait()`` on anything other than the held
  object
- RPC calls (the project's ``.call()`` / ``.call2()`` idiom,
  ``urlopen``, ``getresponse``, ``request``, socket ``connect`` /
  ``recv`` / ``sendall``)
- blocking filesystem work (``open``, ``os.replace`` / ``rename`` /
  ``fsync`` / ``listdir``, file ``.read`` / ``.write`` / ``.flush``,
  ``json.dump`` / ``json.load`` on streams)
- ``subprocess`` invocations

and any ``<lock>.acquire()`` call outside a ``with`` header (manual
acquire/release pairing is what the runtime lockgraph exists to audit;
static code should use ``with``). Waive a deliberate site with
``# lock-ok: <reason>`` — e.g. a dedicated serialization lock that
guards no hot state.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .engine import Finding

KEY = "lock"

_LOCK_CTORS = {"Lock", "RLock"}
_EXCLUDE_CTORS = {"Condition", "Semaphore", "BoundedSemaphore", "Event",
                  "Barrier"}

_BLOCKING_ATTRS = {
    "sleep", "result", "wait", "call", "call2", "urlopen", "getresponse",
    "request", "connect", "recv", "sendall", "read", "readinto",
    "write", "flush", "fsync", "replace", "rename", "listdir",
    "dump", "load", "run", "check_call", "check_output", "communicate",
    "read_chunks", "send_now",
}
_BLOCKING_NAMES = {"sleep", "open"}


class LockLint:
    name = "lock-lint"

    def applies(self, relpath: str) -> bool:
        return True  # lock discipline is repo-wide

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        lock_vars, lock_attrs, excluded = _collect_lock_names(ctx)
        if not lock_vars and not lock_attrs:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.With):
                yield from self._check_with(ctx, node, lock_vars,
                                            lock_attrs, excluded)
            elif isinstance(node, ast.Call):
                yield from self._check_bare_acquire(
                    ctx, node, lock_vars, lock_attrs
                )

    def _is_lock_expr(self, expr, lock_vars, lock_attrs, excluded):
        if isinstance(expr, ast.Name):
            return expr.id in lock_vars and expr.id not in excluded
        if isinstance(expr, ast.Attribute):
            return expr.attr in lock_attrs and expr.attr not in excluded
        return False

    def _check_with(self, ctx, node: ast.With, lock_vars, lock_attrs,
                    excluded) -> Iterator[Finding]:
        held = [item.context_expr for item in node.items
                if self._is_lock_expr(item.context_expr, lock_vars,
                                      lock_attrs, excluded)]
        if not held:
            return
        held_names = {astutil.dotted_name(h) for h in held}
        lock_desc = ", ".join(sorted(held_names))
        for sub in _walk_no_defs(node.body):
            if not isinstance(sub, ast.Call):
                continue
            blocked = self._blocking_reason(sub, held_names)
            if blocked is None:
                continue
            if ctx.annotation(KEY, sub.lineno) is not None:
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue  # whole-with waiver on the `with` line
            yield Finding(
                rule=self.name, path=ctx.relpath, line=sub.lineno,
                col=sub.col_offset, scope=ctx.scope_of(sub),
                message=(
                    f"{blocked} while holding lock {lock_desc} — "
                    f"move the blocking work outside the critical "
                    f"section or waive with '# lock-ok: <reason>'"
                ),
                snippet=ctx.line_text(sub.lineno),
            )

    def _blocking_reason(self, call: ast.Call,
                         held_names: set[str]) -> str | None:
        name = astutil.call_name(call)
        if isinstance(call.func, ast.Name):
            if name in _BLOCKING_NAMES:
                return f"blocking call {name}()"
            return None
        if name not in _BLOCKING_ATTRS:
            return None
        recv = astutil.receiver_of(call)
        recv_name = astutil.dotted_name(recv) if recv is not None else ""
        # .wait() on the held object itself would be a with-Condition
        # pattern; Conditions are excluded from the lock set anyway,
        # but keep the guard for odd aliasing.
        if name == "wait" and recv_name in held_names:
            return None
        # str.join-style false positives: literal receivers are never
        # blocking handles.
        if isinstance(recv, ast.Constant):
            return None
        return f"blocking call .{name}()"

    def _check_bare_acquire(self, ctx, node: ast.Call, lock_vars,
                            lock_attrs) -> Iterator[Finding]:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr != "acquire":
            return
        recv = node.func.value
        is_lock = (
            (isinstance(recv, ast.Name) and recv.id in lock_vars)
            or (isinstance(recv, ast.Attribute)
                and recv.attr in lock_attrs)
        )
        if not is_lock:
            return
        if ctx.annotation(KEY, node.lineno) is not None:
            return
        yield Finding(
            rule=self.name, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=ctx.scope_of(node),
            message=(
                f"lock {astutil.dotted_name(recv)} acquired outside "
                f"'with' — exception paths can leak the hold; use a "
                f"with-block or waive with '# lock-ok: <reason>'"
            ),
            snippet=ctx.line_text(node.lineno),
        )


def _collect_lock_names(ctx):
    """Names/attrs assigned threading.Lock()/RLock() anywhere in the
    module, minus anything ALSO assigned an excluded sync primitive
    (a name reused for a Condition must not drag Condition waits in)."""
    lock_vars: set[str] = set()
    lock_attrs: set[str] = set()
    excluded: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.call_name(node.value)
        if ctor not in _LOCK_CTORS | _EXCLUDE_CTORS:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                (lock_vars if ctor in _LOCK_CTORS else excluded).add(
                    tgt.id
                )
            elif isinstance(tgt, ast.Attribute):
                (lock_attrs if ctor in _LOCK_CTORS else excluded).add(
                    tgt.attr
                )
    return lock_vars, lock_attrs, excluded


def _walk_no_defs(body: list):
    """Walk statements without descending into nested function/class
    defs — code in a nested def does not run under the with."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


RULE = LockLint()
