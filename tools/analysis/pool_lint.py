"""pool-lint: every buffer-pool checkout must release on all paths.

The invariant PR 3 enforced by hand (and the chaos soak asserts at
runtime via ``in_use == 0``): a ``pool.acquire()`` whose buffer can be
abandoned on an exception edge leaks pool accounting and re-faults a
fresh multi-MiB buffer on the next batch.

A checkout is a call ``<pool>.acquire()`` where the receiver is
pool-ish: its name contains "pool", or it was assigned from
``BufferPool(...)`` / ``shared_pool(...)`` in the same module.
(ThreadPoolExecutors expose ``submit``, not ``acquire``, so they never
match; threading locks match ``acquire`` but not the pool-ish filter.)

Accepted protection shapes:

- the acquire is inside a ``try`` whose ``finally`` or exception
  handler calls ``.release(...)`` / ``drop(...)``;
- the statement immediately after the acquire-assign is such a
  ``try`` (the ``buf = pool.acquire(); try: ... except: release; raise``
  idiom);
- the acquire feeds a ``return`` / ``yield`` directly (ownership
  transfers to the caller).

Anything else — including ownership handoffs the analyzer cannot see,
like wrapping the buffer into a pipeline item covered by a drop hook —
needs a ``# pool-ok: <reason>`` annotation naming who releases.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .engine import Finding

KEY = "pool"

_RELEASE_NAMES = {"release", "drop", "_release"}


class PoolLint:
    name = "pool-lint"

    def applies(self, relpath: str) -> bool:
        return True  # pools are used across erasure/pipeline/ops

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        pool_names = _pool_assigned_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != "acquire":
                continue
            recv_name = astutil.dotted_name(node.func.value)
            leaf = recv_name.rsplit(".", 1)[-1]
            if "pool" not in leaf.lower() and leaf not in pool_names:
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue
            if self._protected(ctx, node):
                continue
            yield Finding(
                rule=self.name, path=ctx.relpath, line=node.lineno,
                col=node.col_offset, scope=ctx.scope_of(node),
                message=(
                    f"{recv_name}.acquire() has no release on the "
                    f"exception path — wrap in try/finally (or "
                    f"try/except+release+raise), or waive with "
                    f"'# pool-ok: <who releases>'"
                ),
                snippet=ctx.line_text(node.lineno),
            )

    def _protected(self, ctx, node: ast.Call) -> bool:
        stmt = astutil.stmt_of(ctx, node)
        if stmt is None:
            return False
        # Ownership transfer: `return pool.acquire()` / yield.
        if isinstance(stmt, ast.Return):
            return True
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, (ast.Yield, ast.YieldFrom)):
            return True
        # Enclosing try with a releasing finally/handler.
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Try) and _try_releases(anc):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        # Next-sibling try: buf = pool.acquire(); try: ... except: ...
        body, idx = astutil.body_and_index(stmt)
        if body is not None and idx + 1 < len(body):
            nxt = body[idx + 1]
            if isinstance(nxt, ast.Try) and _try_releases(nxt):
                return True
        return False


def _try_releases(try_node: ast.Try) -> bool:
    for blob in [try_node.finalbody] + [h.body for h in
                                        try_node.handlers]:
        for sub in ast.walk(ast.Module(body=list(blob),
                                       type_ignores=[])):
            if isinstance(sub, ast.Call):
                if astutil.call_name(sub) in _RELEASE_NAMES:
                    return True
    return False


# Pool factories whose results are checkout-tracked even when the
# variable name carries no "pool": the in-process recycled pools AND
# the worker plane's shared-memory strip pools (pipeline/workers) —
# a leaked ShmStrip pins a /dev/shm segment, which is strictly worse
# than a leaked heap buffer.
_POOL_FACTORIES = ("BufferPool", "shared_pool", "strip_pool", "ring_pool")


def _pool_assigned_names(ctx) -> set[str]:
    """Names/attrs assigned from a known pool factory call."""
    out: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if astutil.call_name(node.value) not in _POOL_FACTORIES:
            continue
        for tgt in node.targets:
            name = astutil.dotted_name(tgt)
            if name:
                out.add(name.rsplit(".", 1)[-1])
    return out


RULE = PoolLint()
