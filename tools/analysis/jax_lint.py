"""jax-lint: jit dispatch hygiene on the device/mesh hot path.

The device engine's whole throughput story (PR 4/5) is one dispatch per
batch, zero steady-state retraces, donated staged buffers, and async
D2H. Each sub-rule guards one of the ways a refactor silently regresses
that:

- **jit-in-loop / jit-then-call**: ``jax.jit(...)`` constructed inside
  a loop, or immediately invoked (``jax.jit(f)(x)``), compiles at call
  frequency instead of once.
- **uncached jit**: a jit built inside a function with no caching idiom
  in sight (no ``lru_cache``-style decorator, no ``setdefault``/dict
  store of the compiled fn) recompiles per call.
- **non-hashable static arg**: calling a same-module jitted binding
  with a list/dict/set literal in a ``static_argnums`` position raises
  at runtime (or retraces forever with unhashable-workarounds).
- **missing donate_argnums**: jits in the staged-buffer modules
  (device_engine, mesh_engine, parallel/sharded) must donate their
  input batch or the device arena grows per batch.
- **sync D2H in batch loop**: ``np.asarray`` / ``np.array`` /
  ``.block_until_ready()`` on a value dispatched *in the same loop
  body* serializes H2D -> compute -> D2H and kills the overlap ring
  (the correct shape syncs the PREVIOUS iteration's future).

Only modules that textually import jax are checked. Waive deliberate
sites with ``# jax-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .engine import Finding

KEY = "jax"

# Only the SERVING engines: their contract is a host-staged batch the
# caller never reads back, so the device copy must be donated.
# parallel/sharded.py (the SPMD proving ground) keeps device-resident
# stripes the caller reuses — donation is inapplicable there.
DONATE_REQUIRED = {
    "minio_tpu/erasure/device_engine.py",
    "minio_tpu/parallel/mesh_engine.py",
}

_CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}
_DISPATCHY_SUFFIXES = ("_async",)
_DISPATCHY_NAMES = {"device_put"}
_SYNC_CALLS = {"asarray", "array", "block_until_ready"}


class JaxLint:
    name = "jax-lint"

    def applies(self, relpath: str) -> bool:
        return True  # gated on the module actually importing jax

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        if not _imports_jax(ctx):
            return
        jit_bindings: dict[str, tuple] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_jit_call(node):
                yield from self._check_jit_site(ctx, node)
                _record_binding(node, jit_bindings)
            elif isinstance(node, (ast.For, ast.While)):
                yield from self._check_loop_sync(ctx, node)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_static_args(ctx, node,
                                                   jit_bindings)

    # --- jit construction sites ---

    def _check_jit_site(self, ctx, node: ast.Call) -> Iterator[Finding]:
        if ctx.annotation(KEY, node.lineno) is not None:
            return
        parent = getattr(node, "_parent", None)
        if isinstance(parent, ast.Call) and parent.func is node:
            yield self._finding(
                ctx, node,
                "jit-then-call compiles a fresh function per "
                "invocation — bind the jitted fn once and reuse it",
            )
            return
        in_loop = any(isinstance(a, (ast.For, ast.While))
                      for a in ctx.ancestors(node))
        if in_loop:
            yield self._finding(
                ctx, node,
                "jax.jit constructed inside a loop — retrace risk; "
                "hoist the compile out of the loop",
            )
            return
        fn = ctx.enclosing_function(node)
        if fn is not None and not _has_cache_idiom(fn):
            yield self._finding(
                ctx, node,
                f"jax.jit inside {fn.name}() with no compiled-function "
                f"cache (lru_cache / setdefault / keyed dict store) — "
                f"recompiles at call frequency",
            )
            return
        if ctx.relpath.replace("\\", "/") in DONATE_REQUIRED \
                and not _has_kw(node, "donate_argnums"):
            yield self._finding(
                ctx, node,
                "staged-buffer jit without donate_argnums — the device "
                "arena grows by one input batch per dispatch",
            )

    # --- non-hashable static args at same-module call sites ---

    def _check_static_args(self, ctx, node: ast.Call,
                           bindings: dict) -> Iterator[Finding]:
        if not isinstance(node.func, ast.Name):
            return
        info = bindings.get(node.func.id)
        if info is None:
            return
        static_positions = info
        for pos in static_positions:
            if pos < len(node.args) and isinstance(
                    node.args[pos],
                    (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)):
                if ctx.annotation(KEY, node.lineno) is not None:
                    continue
                yield self._finding(
                    ctx, node,
                    f"non-hashable literal passed in static_argnums "
                    f"position {pos} of jitted '{node.func.id}' — "
                    f"static args must hash (use a tuple)",
                )

    # --- sync inside the dispatch loop ---

    def _check_loop_sync(self, ctx, loop) -> Iterator[Finding]:
        dispatched: dict[str, int] = {}
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call):
                    cname = astutil.call_name(sub.value)
                    if cname.endswith(_DISPATCHY_SUFFIXES) \
                            or cname in _DISPATCHY_NAMES:
                        for tgt in sub.targets:
                            for n in ast.walk(tgt):
                                if isinstance(n, ast.Name):
                                    dispatched[n.id] = sub.lineno
        if not dispatched:
            return
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                name = astutil.call_name(sub)
                if name not in _SYNC_CALLS:
                    continue
                target = None
                if name == "block_until_ready":
                    target = astutil.receiver_of(sub)
                elif sub.args:
                    target = sub.args[0]
                if not isinstance(target, ast.Name):
                    continue
                disp_line = dispatched.get(target.id)
                if disp_line is None or sub.lineno <= disp_line:
                    continue  # syncing a PREVIOUS iteration's future
                if ctx.annotation(KEY, sub.lineno) is not None:
                    continue
                yield self._finding(
                    ctx, sub,
                    f"synchronous D2H of '{target.id}' in the same "
                    f"loop iteration that dispatched it — serializes "
                    f"transfer/compute; sync the previous batch "
                    f"instead",
                )

    def _finding(self, ctx, node, msg) -> Finding:
        return Finding(
            rule=self.name, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=ctx.scope_of(node),
            message=msg, snippet=ctx.line_text(node.lineno),
        )


def _imports_jax(ctx) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax"
                                or node.module.startswith("jax.")):
                return True
    return False


def _is_jit_call(node: ast.Call) -> bool:
    name = astutil.call_name(node)
    if name not in ("jit", "pjit"):
        return False
    # `jax.jit(...)` / `jit(...)` / `pjit.pjit(...)` all count; plain
    # method calls named .jit on arbitrary objects do not exist in
    # this codebase.
    return True


def _has_kw(node: ast.Call, kw: str) -> bool:
    return any(k.arg == kw for k in node.keywords)


def _has_cache_idiom(fn) -> bool:
    for dec in fn.decorator_list:
        d = astutil.dotted_name(dec if not isinstance(dec, ast.Call)
                                else dec.func)
        if d.rsplit(".", 1)[-1] in _CACHE_DECORATORS:
            return True
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) \
                and astutil.call_name(sub) == "setdefault":
            return True
        if isinstance(sub, ast.Assign):
            if any(isinstance(t, ast.Subscript) for t in sub.targets):
                return True
    return False


def _record_binding(node: ast.Call, bindings: dict) -> None:
    """`g = jax.jit(f, static_argnums=(0, 2))` -> bindings["g"] =
    (0, 2), so later same-module calls of g can be checked."""
    parent = getattr(node, "_parent", None)
    if not isinstance(parent, ast.Assign):
        return
    if len(parent.targets) != 1 \
            or not isinstance(parent.targets[0], ast.Name):
        return
    positions: list[int] = []
    for k in node.keywords:
        if k.arg != "static_argnums":
            continue
        vals = (k.value.elts if isinstance(k.value, ast.Tuple)
                else [k.value])
        for v in vals:
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                positions.append(v.value)
    if positions:
        bindings[parent.targets[0].id] = tuple(positions)


RULE = JaxLint()
