"""Module-level dataflow engine for the flow-sensitive lint rules.

PR6's rules check single statements; the bugs left in the hot path are
*flow* properties — a pooled buffer used after its release point, a
shm payload view smuggled into a pipe write three assignments later, a
guarded field read outside its lock. This module provides the shared
machinery those rules interpret programs with:

- **FlowWalker** — an abstract-interpretation skeleton over ONE
  function body: statements execute in order against a mutable
  ``State``; ``If`` forks and joins (union of may-facts), loops run
  their body twice so loop-carried facts (release at the bottom, use
  at the top) surface, ``try`` handlers see a merge of entry and body
  effects, ``finally`` runs on the joined state, and ``return`` /
  ``raise`` / ``break`` / ``continue`` kill their path so facts from
  a bailing branch never pollute the fall-through (``except: release;
  raise`` must not mark the buffer released for code after the try).
  Nested function/class defs do NOT execute in the enclosing flow —
  they surface through :meth:`FlowWalker.on_nested_def` (closures run
  at an unknown time; rules decide what escape means).

- **def-use / alias helpers** — ``assigned_names`` (flattened binding
  targets), ``names_in`` (every Name read by an expression),
  ``origins_of`` (which tracked origins an expression may alias,
  through attribute/subscript views, view-producing calls like
  ``memoryview``/``np.frombuffer``/``.reshape``, tuple packing, and
  conditional expressions).

- **LockState** — the lock lattice for guardedby-lint: dotted lock
  names held by ``with`` blocks, with local aliases (``cv = self._cv``)
  canonicalized, merged by intersection (a lock is held only if held
  on every path).

Everything here is intra-procedural by design; the rules add the
narrow inter-procedural summaries they need (shm-lint's return/param
taint, guardedby-lint's method preconditions) on top.
"""

from __future__ import annotations

import ast

from . import astutil

# ---------------------------------------------------------------------------
# def-use / alias helpers

#: Calls that return a VIEW of (not a copy of) their first argument —
#: aliasing flows straight through them.
VIEW_CALLS = {"memoryview", "frombuffer"}

#: Methods that return a view of their receiver (numpy/memoryview
#: reshaping surface). ``.tobytes()`` & friends COPY — a copy no longer
#: aliases the pooled storage, which is exactly why copy-lint exists.
VIEW_METHODS = {"reshape", "view", "cast", "ravel", "transpose",
                "squeeze", "astype_view", "recon_src", "recon_out",
                "recon_digests"}


def stmt_exprs(stmt) -> list:
    """Expression positions evaluated AT this statement (compound
    bodies excluded — FlowWalker descends into those itself)."""
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [stmt.test]
    return []


def walk_no_defs(expr):
    """Walk an expression without descending into nested defs/lambdas
    (their bodies run later, not here)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def is_nested_function(fn) -> bool:
    """True when `fn` is defined inside another function — its body
    executes through the enclosing flow's on_nested_def hook, so
    whole-module rule drivers must not ALSO walk it directly."""
    cur = getattr(fn, "_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return True
        cur = getattr(cur, "_parent", None)
    return False


def assigned_names(target: ast.AST) -> list[ast.Name]:
    """Flattened Name targets of an assignment (tuple/list unpacking
    included; starred targets unwrap; attribute/subscript stores are
    heap escapes, not local bindings, and are omitted)."""
    out: list[ast.Name] = []
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, ast.Name):
            out.append(t)
        elif isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
    return out


def names_in(expr: ast.AST) -> set[str]:
    """Every Name read anywhere inside `expr` (nested defs excluded —
    their reads happen at call time, not here)."""
    out: set[str] = set()
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return out


def free_names_of_def(fn: ast.AST) -> set[str]:
    """Names a nested def/lambda READS but never binds — the closure
    captures that can smuggle a buffer view into another thread."""
    bound: set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            bound.add(a.arg)
        if args.vararg:
            bound.add(args.vararg.arg)
        if args.kwarg:
            bound.add(args.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    reads: set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    reads.add(node.id)
                else:
                    bound.add(node.id)
    return reads - bound


def origins_of(expr: ast.AST, env: dict[str, frozenset]) -> frozenset:
    """Which tracked origins `expr` may alias under the name
    environment `env` (name -> frozenset of origin keys).

    Aliasing propagates through: bare names, attribute/subscript loads
    (a view of pooled storage IS the pooled storage), view-producing
    calls and methods (memoryview/frombuffer/.reshape/...), tuple/list
    packing, conditional expressions, and named-expression walrus
    binds. Ordinary calls BREAK the chain — ``len(buf)`` does not
    alias ``buf`` — which keeps the rules' false-positive rate at the
    level a tier-1 gate needs.
    """
    out: set = set()
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Name):
            out.update(env.get(node.id, ()))
        elif isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            stack.append(node.value)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            stack.extend(node.elts)
        elif isinstance(node, ast.Dict):
            stack.extend(v for v in node.values if v is not None)
        elif isinstance(node, ast.IfExp):
            stack.extend((node.body, node.orelse))
        elif isinstance(node, ast.BinOp):
            stack.extend((node.left, node.right))
        elif isinstance(node, ast.NamedExpr):
            stack.append(node.value)
        elif isinstance(node, ast.Await):
            stack.append(node.value)
        elif isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name in VIEW_CALLS or name in VIEW_METHODS:
                if isinstance(node.func, ast.Attribute):
                    stack.append(node.func.value)
                stack.extend(node.args)
            # other calls: alias chain intentionally broken
    return frozenset(out)


# ---------------------------------------------------------------------------
# abstract state + walker

class State:
    """Base abstract state. Subclasses add fact fields and implement
    copy()/merge_from(). `dead` marks a terminated path (return/raise/
    break/continue) whose facts must not join the fall-through."""

    __slots__ = ("dead",)

    def __init__(self):
        self.dead = False

    def copy(self) -> "State":
        raise NotImplementedError

    def merge_from(self, other: "State") -> None:
        raise NotImplementedError


def merge_states(states: list) -> "State | None":
    """Join the LIVE states of a fork; None when every path died."""
    live = [s for s in states if s is not None and not s.dead]
    if not live:
        return None
    out = live[0]
    for s in live[1:]:
        out.merge_from(s)
    return out


class FlowWalker:
    """Abstract-interpretation skeleton; rules subclass the hooks.

    The walker owns control flow only. It calls:

    - on_stmt(stmt, state)        every statement, including compound
                                  headers (the If test, the For iter,
                                  the With items) BEFORE descending;
    - on_assign(stmt, state)      Assign/AugAssign/AnnAssign, after
                                  on_stmt;
    - on_return(stmt, state)      Return, before the path dies;
    - on_with_enter/exit          around With bodies;
    - on_nested_def(node, state)  FunctionDef/Lambda/ClassDef seen in
                                  the flow (not descended into).

    `finally_stack` exposes the finalbody lists of every enclosing
    try-with-finally at the current point — on_return hooks use it to
    see releases that WILL run after the return value is computed.
    """

    def __init__(self, ctx: astutil.ModuleContext):
        self.ctx = ctx
        self.finally_stack: list[list] = []

    # -- hooks (default no-ops) --------------------------------------------

    def on_stmt(self, stmt, state) -> None:
        pass

    def on_assign(self, stmt, state) -> None:
        pass

    def on_return(self, stmt, state) -> None:
        pass

    def on_with_enter(self, node, state) -> None:
        pass

    def on_with_exit(self, node, state) -> None:
        pass

    def on_nested_def(self, node, state) -> None:
        pass

    # -- driver -------------------------------------------------------------

    def walk_function(self, fn, state: State) -> State | None:
        """Interpret one function body; returns the fall-through state
        (None when every path returned/raised)."""
        return self._exec_body(fn.body, state)

    def _exec_body(self, body: list, state: State | None):
        for stmt in body:
            if state is None or state.dead:
                return state
            state = self._exec_stmt(stmt, state)
        return state

    def _exec_stmt(self, stmt, state: State):
        self.on_stmt(stmt, state)
        if isinstance(stmt, ast.If):
            s_then = state.copy()
            s_then = self._exec_body(stmt.body, s_then)
            s_else = state.copy()
            s_else = self._exec_body(stmt.orelse, s_else)
            merged = merge_states([s_then, s_else])
            if merged is None:
                state.dead = True
                return state
            return merged
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._exec_loop(stmt, state)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, state)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.on_with_enter(stmt, state)
            out = self._exec_body(stmt.body, state)
            if out is not None:
                self.on_with_exit(stmt, out)
            return out if out is not None else state
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self.on_nested_def(stmt, state)
            return state
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self.on_assign(stmt, state)
            return state
        if isinstance(stmt, ast.Return):
            self.on_return(stmt, state)
            state.dead = True
            return state
        if isinstance(stmt, (ast.Raise, ast.Break, ast.Continue)):
            state.dead = True
            return state
        return state

    def _exec_loop(self, stmt, state: State):
        # Two passes over the body: pass 1 from the entry state, pass 2
        # from pass 1's exit so loop-carried facts (released at the
        # bottom, used at the top) meet. break/continue inside kill
        # only their pass — the loop as a whole still falls through.
        skip = state.copy()  # zero-iteration path
        s1 = state.copy()
        s1.dead = False
        s1 = self._exec_body(stmt.body, s1)
        if s1 is not None and not s1.dead:
            s2 = s1.copy()
            s2 = self._exec_body(stmt.body, s2)
            if s2 is not None and not s2.dead:
                s1 = s2
        out = merge_states([skip, s1])
        if out is None:
            out = skip
            out.dead = False
        if stmt.orelse:
            out = self._exec_body(stmt.orelse, out) or out
        return out

    def _exec_try(self, stmt: ast.Try, state: State):
        if stmt.finalbody:
            self.finally_stack.append(stmt.finalbody)
        try:
            entry = state.copy()
            body_state = self._exec_body(stmt.body, state)
            handler_states = []
            for h in stmt.handlers:
                # A handler can enter after ANY prefix of the body ran:
                # approximate its entry as entry ∪ end-of-body facts.
                hs = entry.copy()
                if body_state is not None:
                    hs.merge_from(body_state)
                hs.dead = False
                hs = self._exec_body(h.body, hs)
                handler_states.append(hs)
            if (body_state is not None and not body_state.dead
                    and stmt.orelse):
                body_state = self._exec_body(stmt.orelse, body_state)
            out = merge_states([body_state] + handler_states)
        finally:
            if stmt.finalbody:
                self.finally_stack.pop()
        if out is None:
            # Every path bailed; the finally still runs, but nothing
            # flows past the try.
            dead = entry
            dead.dead = True
            if stmt.finalbody:
                dead.dead = False
                dead = self._exec_body(stmt.finalbody, dead) or dead
                dead.dead = True
            return dead
        if stmt.finalbody:
            out = self._exec_body(stmt.finalbody, out) or out
        return out


# ---------------------------------------------------------------------------
# lock lattice (guardedby-lint)

class LockState(State):
    """Lock names held at the current point with HOLD COUNTS (nested
    ``with`` on one re-entrant lock must not un-hold it at the inner
    exit), plus local aliases (``cv = self._cv`` makes ``with cv:``
    count as holding self._cv). Merge = intersection: a guard only
    counts when EVERY path holds it."""

    __slots__ = ("held", "aliases")

    def __init__(self, held=None):
        super().__init__()
        # dotted lock name -> nesting depth
        self.held: dict[str, int] = dict(held or {})
        self.aliases: dict[str, str] = {}

    def copy(self) -> "LockState":
        s = LockState(self.held)
        s.aliases = dict(self.aliases)
        s.dead = self.dead
        return s

    def merge_from(self, other: "LockState") -> None:
        self.held = {
            name: min(depth, other.held[name])
            for name, depth in self.held.items()
            if name in other.held
        }
        self.aliases = {k: v for k, v in self.aliases.items()
                        if other.aliases.get(k) == v}

    def hold(self, name: str) -> None:
        self.held[name] = self.held.get(name, 0) + 1

    def unhold(self, name: str) -> None:
        depth = self.held.get(name, 0)
        if depth <= 1:
            self.held.pop(name, None)
        else:
            self.held[name] = depth - 1

    def canonical(self, expr: ast.AST) -> str:
        name = astutil.dotted_name(expr)
        return self.aliases.get(name, name)

    def note_alias(self, stmt: ast.Assign) -> None:
        """Record ``x = self._mu``-shaped lock aliases (and kill stale
        aliases on any other rebind of x)."""
        if not isinstance(stmt, ast.Assign):
            return
        value_name = astutil.dotted_name(stmt.value)
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                if value_name:
                    self.aliases[tgt.id] = self.aliases.get(
                        value_name, value_name
                    )
                else:
                    self.aliases.pop(tgt.id, None)

    def holds(self, lockname: str) -> bool:
        """True when `lockname` (a declaration like ``_mu`` or
        ``self._mu``) matches any held lock by dotted-leaf equality —
        declarations name the field, with-blocks name the access
        path."""
        leaf = lockname.rsplit(".", 1)[-1]
        for h in self.held:
            if h == lockname or h.rsplit(".", 1)[-1] == leaf:
                return True
        return False
