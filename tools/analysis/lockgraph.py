"""Runtime lock-order checker: instrumented lock wrappers that record
the per-thread acquisition graph, detect cycles (potential deadlocks),
and report hold-time outliers.

Locks are keyed by ALLOCATION SITE (the ``file:line`` that called
``threading.Lock()``), not by instance — the classic lockdep
abstraction: two locks born at one site form a lock *class*, and an
A->B plus B->A ordering between two classes is a deadlock waiting for
the right interleaving even if this run never deadlocked. Reentrant
holds of the same *instance* (RLock) add no edge; nesting two distinct
instances of the same class is recorded as a self-edge and reported
separately (``self_nesting``) rather than as a cycle, since ordered
same-class nesting (e.g. parent->child) is legitimate.

Enable by monkeypatching the factories::

    from tools.analysis import lockgraph
    lockgraph.enable()            # or enable_from_env(): MTPU_LOCK_CHECK=1
    ...
    report = lockgraph.report()   # {"cycles": [...], "hold_outliers": ...}
    lockgraph.disable()

Only locks CREATED while enabled are tracked (module-level locks born
at import time are not — arm early). ``threading.Condition()`` default
locks are created through the patched ``RLock`` and tracked under the
threading.py call site. The wrapper passes through ``_release_save`` /
``_acquire_restore`` / ``_is_owned`` semantics so Condition.wait keeps
working and the held-stack stays truthful across waits.

Armed in tests/test_race_stress.py and tests/test_chaos_soak.py; the
suites assert zero acquisition-graph cycles after driving the risky
interleavings hard.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

HOLD_OUTLIER_S = 0.1  # report holds longer than this


class LockGraph:
    """Global acquisition graph over lock classes (allocation sites)."""

    def __init__(self):
        # The graph's own mutex uses the REAL lock type: instrumenting
        # it would recurse.
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self.edges: dict[tuple[str, str], int] = {}
        self.self_nesting: dict[str, int] = {}
        self.holds: dict[str, dict] = {}  # site -> count/total/max
        self.acquisitions = 0

    # --- per-thread held stack: list of (site, lock_id, t0) ---

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def note_acquired(self, site: str, lock_id: int) -> None:
        stack = self._stack()
        held_ids = [lid for (_s, lid, _t) in stack]
        if lock_id in held_ids:
            # Reentrant hold of the same instance (RLock): no new
            # ordering information; push for release pairing only.
            stack.append((site, lock_id, time.monotonic()))
            return
        new_edges = []
        self_nest = False
        for held_site, _lid, _t in stack:
            if held_site == site:
                self_nest = True
            else:
                new_edges.append((held_site, site))
        stack.append((site, lock_id, time.monotonic()))
        if not new_edges and not self_nest:
            with self._mu:
                self.acquisitions += 1
            return
        with self._mu:
            self.acquisitions += 1
            for e in new_edges:
                self.edges[e] = self.edges.get(e, 0) + 1
            if self_nest:
                self.self_nesting[site] = (
                    self.self_nesting.get(site, 0) + 1
                )

    def note_released(self, site: str, lock_id: int) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == lock_id:
                _s, _lid, t0 = stack.pop(i)
                held = time.monotonic() - t0
                with self._mu:
                    h = self.holds.setdefault(
                        site, {"count": 0, "total_s": 0.0, "max_s": 0.0}
                    )
                    h["count"] += 1
                    h["total_s"] += held
                    if held > h["max_s"]:
                        h["max_s"] = held
                return

    # --- analysis ---

    def cycles(self) -> list[list[str]]:
        """Elementary cycles in the site graph (self-edges excluded —
        reported via self_nesting). DFS with a path stack; graphs here
        are tiny (dozens of sites)."""
        with self._mu:
            adj: dict[str, set[str]] = {}
            for (a, b) in self.edges:
                if a != b:
                    adj.setdefault(a, set()).add(b)
        found: list[list[str]] = []
        seen_keys: set[tuple] = set()

        def dfs(start: str, node: str, path: list[str],
                on_path: set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = path[:]
                    key = tuple(sorted(cyc))
                    if key not in seen_keys:
                        seen_keys.add(key)
                        found.append(cyc + [start])
                elif nxt not in on_path and nxt > start:
                    # Only explore nodes ordered after start so each
                    # cycle is found from its smallest node exactly once.
                    path.append(nxt)
                    on_path.add(nxt)
                    dfs(start, nxt, path, on_path)
                    on_path.discard(nxt)
                    path.pop()

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return found

    def hold_outliers(self, threshold_s: float = HOLD_OUTLIER_S) -> list:
        with self._mu:
            out = [
                {"site": site, "max_hold_s": round(h["max_s"], 4),
                 "mean_hold_s": round(h["total_s"] / h["count"], 6),
                 "count": h["count"]}
                for site, h in self.holds.items()
                if h["max_s"] >= threshold_s
            ]
        out.sort(key=lambda d: -d["max_hold_s"])
        return out

    def report(self, outlier_threshold_s: float = HOLD_OUTLIER_S) -> dict:
        cycles = self.cycles()
        with self._mu:
            n_edges = len(self.edges)
            n_acq = self.acquisitions
            self_nest = dict(self.self_nesting)
        return {
            "acquisitions": n_acq,
            "edges": n_edges,
            "cycles": cycles,
            "self_nesting": self_nest,
            "hold_outliers": self.hold_outliers(outlier_threshold_s),
        }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.self_nesting.clear()
            self.holds.clear()
            self.acquisitions = 0


GRAPH = LockGraph()


class CheckedLock:
    """Duck-typed Lock/RLock wrapper feeding the global graph. Supports
    the Condition protocol (_release_save/_acquire_restore/_is_owned)
    so patched factories keep threading.Condition working."""

    __slots__ = ("_lock", "_site", "_reentrant")

    def __init__(self, site: str, reentrant: bool):
        self._lock = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self._site = site
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            GRAPH.note_acquired(self._site, id(self))
        return ok

    def release(self):
        # Pop our accounting BEFORE the real release: after release,
        # another thread may acquire and we'd race the stack.
        GRAPH.note_released(self._site, id(self))
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    # --- Condition protocol passthroughs ---

    def _release_save(self):
        GRAPH.note_released(self._site, id(self))
        if hasattr(self._lock, "_release_save"):
            return self._lock._release_save()
        self._lock.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._lock, "_acquire_restore"):
            self._lock._acquire_restore(state)
        else:
            self._lock.acquire()
        GRAPH.note_acquired(self._site, id(self))

    def _is_owned(self):
        if hasattr(self._lock, "_is_owned"):
            return self._lock._is_owned()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _at_fork_reinit(self):
        # stdlib registers lock._at_fork_reinit as an os fork handler
        # (concurrent.futures.thread does at import) — must exist.
        self._lock._at_fork_reinit()

    def __getattr__(self, name):
        # Fallback for any other stdlib-internal lock attribute; plain
        # lookups (slots above) never reach here.
        return getattr(object.__getattribute__(self, "_lock"), name)

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"<CheckedLock {kind} site={self._site}>"


def _caller_site() -> str:
    """file:line of the first frame outside this module — the lock's
    allocation site / class key."""
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fn = f.f_code.co_filename
    # Compress to the repo-relative tail for stable, readable keys.
    parts = fn.replace("\\", "/").rsplit("/", 3)
    return f"{'/'.join(parts[-2:])}:{f.f_lineno}"


def _checked_lock():
    return CheckedLock(_caller_site(), reentrant=False)


def _checked_rlock():
    return CheckedLock(_caller_site(), reentrant=True)


_enabled = False


def enable() -> None:
    """Patch threading.Lock/RLock so every lock created from now on is
    tracked. Idempotent."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    threading.Lock = _checked_lock
    threading.RLock = _checked_rlock


def disable() -> None:
    """Restore the real factories. Tracked locks already created keep
    working (and keep reporting) — only new creations stop."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK


def enabled() -> bool:
    return _enabled


def enable_from_env() -> bool:
    """Arm iff MTPU_LOCK_CHECK=1 — the production/ops knob documented
    in docs/ANALYSIS.md."""
    if os.environ.get("MTPU_LOCK_CHECK", "0") == "1":
        enable()
        return True
    return False


def report(outlier_threshold_s: float = HOLD_OUTLIER_S) -> dict:
    return GRAPH.report(outlier_threshold_s)


def reset() -> None:
    GRAPH.reset()


def assert_no_cycles() -> None:
    cyc = GRAPH.cycles()
    if cyc:
        raise AssertionError(
            f"lock acquisition-order cycles detected: {cyc}"
        )
