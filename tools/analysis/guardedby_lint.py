"""guardedby-lint: declared lock invariants, verified at every site.

The static complement of the MTPU_LOCK_CHECK runtime lockgraph: the
lockgraph convicts orderings it OBSERVES; this rule proves every read
and write of a declared shared field happens under its lock, on every
path, without needing the racy interleaving to occur in a test run.

Declaration grammar (the comment sits on the field's initialization
line, or on a ``def`` line for a method precondition)::

    self._workers = []          # guarded-by: _mu
    _slow_store = deque(...)    # guarded-by: _slow_mu     (module var)
    def _grant_to(self, c):     # guarded-by: _cv          (precondition)
    self._inflight = 0          # guarded-by: _tokens_cv|_lock

- A **field declaration** binds the attribute (``self.<field>`` in the
  declaring class) or module-level name to a lock. Every load/store of
  it outside ``__init__`` must execute with the lock held.
- A **method precondition** (``# guarded-by:`` on the ``def`` line)
  asserts callers hold the lock: the method body is checked WITH the
  lock assumed held, and every call site of the method is checked to
  actually hold it.
- ``|`` alternation accepts any one of several names for the same
  underlying lock (``threading.Condition(self._lock)`` makes
  ``_tokens_cv`` and ``_lock`` the same mutex).

Lock state is tracked intra-procedurally by the dataflow engine's
LockState lattice: ``with self._mu:`` / ``with cv:`` (through local
aliases like ``cv = self._cv``) holds the lock for the block; branch
joins require the lock held on EVERY path. Nested defs are checked
with an empty lock state — a closure runs at an unknown time.

Benign racy reads (telemetry snapshots, double-checked fast paths)
are waived in place with ``# guardedby-ok: <reason>`` — the point is
that every unlocked access is either a bug or carries its reasoning.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil, dataflow
from .engine import Finding

KEY = "guardedby"

#: Methods exempt from field checks: construction and teardown run
#: before/after the object is shared.
_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}


class _Decl:
    __slots__ = ("locks", "line")

    def __init__(self, spec: str, line: int):
        self.locks = tuple(spec.split("|"))
        self.line = line

    def satisfied(self, state: dataflow.LockState) -> bool:
        return any(state.holds(lk) for lk in self.locks)

    @property
    def spec(self) -> str:
        return "|".join(self.locks)


class GuardedByLint:
    name = "guardedby-lint"

    def applies(self, relpath: str) -> bool:
        return True  # only modules carrying declarations produce work

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        if not ctx.guards:
            return
        module_fields, class_fields, method_pre = _collect_decls(ctx)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            # Nested defs execute through the enclosing walker's
            # on_nested_def; walking them here too would report each
            # access twice.
            if dataflow.is_nested_function(node):
                continue
            cls = _enclosing_class(node)
            fields = dict(module_fields)
            pre: dict[str, _Decl] = {}
            if cls is not None and cls.name in class_fields:
                if node.name not in _EXEMPT_METHODS:
                    fields.update(class_fields[cls.name])
                pre = method_pre.get(cls.name, {})
            elif cls is not None:
                pre = method_pre.get(cls.name, {})
            if not fields and not pre:
                continue
            walker = _GuardWalk(ctx, fields, pre, cls, findings)
            seed = dataflow.LockState()
            own_pre = pre.get(node.name)
            if own_pre is not None:
                # The precondition holds at entry, by contract.
                for lk in own_pre.locks:
                    seed.hold(lk)
            walker.walk_function(node, seed)
        yield from findings


def _enclosing_class(fn) -> ast.ClassDef | None:
    cur = getattr(fn, "_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None  # nested def: not a method
        cur = getattr(cur, "_parent", None)
    return None


def _collect_decls(ctx):
    """(module_fields, class_fields, method_preconditions) from the
    `# guarded-by:` declarations: the declaration line's statement
    decides what is being declared."""
    module_fields: dict[str, _Decl] = {}
    class_fields: dict[str, dict[str, _Decl]] = {}
    method_pre: dict[str, dict[str, _Decl]] = {}
    for node in ast.walk(ctx.tree):
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            continue
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Assign, ast.AnnAssign)):
            continue
        # The declaration comment may sit on any physical line of the
        # statement (multi-line initializers put it on the closing
        # paren); defs match only their header line, not their body.
        end = lineno if isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
            else (node.end_lineno or lineno)
        spec = None
        decl_line = lineno
        for ln in range(lineno, end + 1):
            if ln in ctx.guards:
                spec = ctx.guards[ln]
                decl_line = ln
                break
        if spec is None:
            continue
        lineno = decl_line
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = _enclosing_class(node)
            if cls is not None:
                method_pre.setdefault(cls.name, {})[node.name] = _Decl(
                    spec, lineno
                )
            continue
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                # The declaring class is the one whose method (usually
                # __init__) performs the annotated assignment.
                cur = getattr(node, "_parent", None)
                while cur is not None and not isinstance(cur,
                                                         ast.ClassDef):
                    cur = getattr(cur, "_parent", None)
                if cur is not None:
                    class_fields.setdefault(cur.name, {})[tgt.attr] = (
                        _Decl(spec, lineno)
                    )
            elif isinstance(tgt, ast.Name):
                # Module-level declaration only (function locals are
                # thread-private).
                parent = getattr(node, "_parent", None)
                if isinstance(parent, ast.Module):
                    module_fields[tgt.id] = _Decl(spec, lineno)
    return module_fields, class_fields, method_pre


class _GuardWalk(dataflow.FlowWalker):
    def __init__(self, ctx, fields: dict, pre: dict, cls, findings):
        super().__init__(ctx)
        self.fields = fields
        self.pre = pre
        self.cls = cls
        self.findings = findings
        self._seen: set[tuple] = set()

    # -- lock tracking -------------------------------------------------------

    def on_with_enter(self, node, state: dataflow.LockState) -> None:
        for item in node.items:
            expr = item.context_expr
            # `with self._mu:` and `with lock.acquire_ctx()`-free shapes;
            # a Call context (e.g. `with open(...)`) is not a lock hold.
            if isinstance(expr, (ast.Name, ast.Attribute)):
                state.hold(state.canonical(expr))

    def on_with_exit(self, node, state: dataflow.LockState) -> None:
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, (ast.Name, ast.Attribute)):
                state.unhold(state.canonical(expr))

    def on_assign(self, stmt, state: dataflow.LockState) -> None:
        if isinstance(stmt, ast.Assign):
            state.note_alias(stmt)

    # -- access checking -----------------------------------------------------

    def on_stmt(self, stmt, state: dataflow.LockState) -> None:
        for expr in dataflow.stmt_exprs(stmt):
            for node in dataflow.walk_no_defs(expr):
                self._check_node(node, state)
        # Assignment/augassign targets are accesses too.
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                for node in dataflow.walk_no_defs(tgt):
                    self._check_node(node, state)

    def on_nested_def(self, node, state) -> None:
        # A closure executes at an unknown time: check its body with
        # an EMPTY lock state (anything guarded it touches must be
        # waived or restructured).
        walker = _GuardWalk(self.ctx, self.fields, self.pre, self.cls,
                            self.findings)
        walker.walk_function(node, dataflow.LockState())

    def _check_node(self, node, state: dataflow.LockState) -> None:
        decl = None
        what = ""
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in self.fields:
            decl = self.fields[node.attr]
            what = f"self.{node.attr}"
        elif isinstance(node, ast.Name) and node.id in self.fields:
            decl = self.fields[node.id]
            what = node.id
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr in self.pre:
            pdecl = self.pre[node.func.attr]
            if not pdecl.satisfied(state):
                self._emit(
                    node, pdecl,
                    f"call to self.{node.func.attr}() which requires "
                    f"lock '{pdecl.spec}' (declared at line "
                    f"{pdecl.line}) without holding it",
                )
            return
        if decl is None:
            return
        # The declaration line itself initializes the field.
        if node.lineno == decl.line:
            return
        if not decl.satisfied(state):
            self._emit(
                node, decl,
                f"access to {what} outside its declared lock "
                f"'{decl.spec}' (guarded-by at line {decl.line}) — "
                f"hold the lock, or waive a benign racy read with "
                f"'# guardedby-ok: <reason>'",
            )

    def _emit(self, node, decl, message: str) -> None:
        key = (node.lineno, node.col_offset, message)
        if key in self._seen:
            return
        self._seen.add(key)
        if self.ctx.annotation(KEY, node.lineno) is not None:
            return
        self.findings.append(Finding(
            rule="guardedby-lint", path=self.ctx.relpath,
            line=node.lineno, col=node.col_offset,
            scope=self.ctx.scope_of(node), message=message,
            snippet=self.ctx.line_text(node.lineno),
        ))


RULE = GuardedByLint()
