"""knob-lint: every MTPU_* environment knob is documented and has a
declared default.

The deployment surface is env knobs; an undocumented one is a feature
operators cannot find and a default nobody agreed to. Two checks on
every ``MTPU_*`` read in ``minio_tpu/``:

- **documented** — the knob name appears in ``docs/DEPLOYMENT.md`` or
  ``docs/OBSERVABILITY.md`` (or ``docs/ANALYSIS.md`` for the analysis
  plane's own knobs);
- **default declared** — the read supplies a default at the call site:
  ``os.environ.get("MTPU_X", <default>)`` / ``os.getenv("MTPU_X",
  <default>)``. Bare ``os.environ["MTPU_X"]`` or a get() with no
  second argument fires — a missing knob must mean the documented
  default, never a KeyError or a None surprise.

Writes (``os.environ["MTPU_X"] = ...``, ``.pop``, ``.setdefault``)
are not reads and are ignored. Waive a deliberate site with
``# knob-ok: <reason>``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from . import astutil
from .engine import Finding, repo_root

KEY = "knob"

DOC_FILES = ("docs/DEPLOYMENT.md", "docs/OBSERVABILITY.md",
             "docs/ANALYSIS.md")

_doc_cache: dict[str, str] = {}


def _docs_text() -> str:
    root = repo_root()
    out = []
    for rel in DOC_FILES:
        path = os.path.join(root, rel)
        cached = _doc_cache.get(path)
        if cached is None:
            try:
                with open(path, encoding="utf-8") as f:
                    cached = f.read()
            except OSError:
                cached = ""
            _doc_cache[path] = cached
        out.append(cached)
    return "\n".join(out)


class KnobLint:
    name = "knob-lint"

    def applies(self, relpath: str) -> bool:
        # The analysis plane reads its own knobs (MTPU_LOCK_CHECK) and
        # docs/ANALYSIS.md is in DOC_FILES exactly for them: hold
        # tools/ to the same standard as the package.
        rel = relpath.replace("\\", "/")
        return rel.startswith(("minio_tpu/", "tools/"))

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        docs = None  # loaded lazily: most modules read no knobs
        for node in ast.walk(ctx.tree):
            knob, has_default = _knob_read(node)
            if knob is None:
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue
            if docs is None:
                docs = _docs_text()
            # Whole-word match: docs naming MTPU_TRACE_SLOW_MS must not
            # count as documenting MTPU_TRACE (underscore is a word
            # char, so \b rejects the prefix-of-longer-knob case).
            if not re.search(rf"\b{re.escape(knob)}\b", docs):
                yield self._finding(
                    ctx, node,
                    f"env knob {knob} is read here but documented "
                    f"nowhere — add it (name, default, effect) to "
                    f"docs/DEPLOYMENT.md or docs/OBSERVABILITY.md",
                )
            if not has_default:
                yield self._finding(
                    ctx, node,
                    f"env knob {knob} is read without a default — "
                    f"use os.environ.get({knob!r}, <default>) so a "
                    f"missing knob means the documented default",
                )

    def _finding(self, ctx, node, message: str) -> Finding:
        return Finding(
            rule=self.name, path=ctx.relpath, line=node.lineno,
            col=getattr(node, "col_offset", 0),
            scope=ctx.scope_of(node), message=message,
            snippet=ctx.line_text(node.lineno),
        )


def _knob_read(node) -> tuple[str | None, bool]:
    """(knob name, default declared) for an env READ node, else
    (None, ...)."""
    # os.environ["MTPU_X"] — a Load-context subscript only.
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and astutil.dotted_name(node.value).endswith("environ"):
        name = _const_knob(node.slice)
        if name:
            return name, False
    if isinstance(node, ast.Call):
        fname = astutil.call_name(node)
        dotted = astutil.dotted_name(node.func)
        is_env_get = (fname == "get" and dotted.endswith("environ.get"))
        is_getenv = (fname == "getenv"
                     and dotted in ("os.getenv", "getenv"))
        if (is_env_get or is_getenv) and node.args:
            name = _const_knob(node.args[0])
            if name:
                return name, len(node.args) > 1 or bool(node.keywords)
    return None, False


def _const_knob(expr) -> str | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and expr.value.startswith("MTPU_"):
        return expr.value
    return None


RULE = KnobLint()
