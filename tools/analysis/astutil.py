"""Shared AST plumbing for the lint rules: parsed-module context with
parent links, comment/annotation maps, and small node helpers.

Waiver annotations are per-rule comments on the flagged line (or the
line above it)::

    x = buf.tobytes()  # copy-ok: put.tail_copy
    # lock-ok: drain serialization lock, guards no hot state
    with self._drain_mu:

The annotation silences the rule at that site; copy-lint additionally
validates that the label names a real CopyCounters site (see
copy_lint.py).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

_ANN_RE = re.compile(
    r"#\s*(copy|lock|pool|jax|except|metrics|lifetime|shm|guardedby|knob)"
    r"-ok:\s*(\S[^#]*)"
)

# Declaration (not waiver) comments consumed by guardedby-lint:
#   self._workers = []   # guarded-by: _mu
#   def _grant_to(...):  # guarded-by: _cv
# The lock spec is one name, optionally `|`-alternated when two names
# reach the same underlying lock (Condition(lock) sharing).
_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*(?:\|[\w.]+)*)")


@dataclass
class ModuleContext:
    """One parsed source file, shared by every rule that scans it."""

    relpath: str
    source: str
    tree: ast.AST
    lines: list[str]
    # lineno -> {rule_key: reason} parsed from `# <rule>-ok:` comments.
    annotations: dict[int, dict[str, str]] = field(default_factory=dict)
    # lineno -> lock spec parsed from `# guarded-by:` declarations.
    guards: dict[int, str] = field(default_factory=dict)

    def annotation(self, rule_key: str, lineno: int) -> str | None:
        """Waiver reason for `rule_key` at `lineno`: the marker may sit
        on the flagged line itself or anywhere in the contiguous
        comment block directly above it."""
        ann = self.annotations.get(lineno)
        if ann and rule_key in ann:
            return ann[rule_key]
        ln = lineno - 1
        while ln >= 1 and self.line_text(ln).startswith("#"):
            ann = self.annotations.get(ln)
            if ann and rule_key in ann:
                return ann[rule_key]
            ln -= 1
        return None

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the enclosing function/class chain —
        the stable half of a finding's fingerprint (line numbers
        shift; scopes rarely do)."""
        parts: list[str] = []
        cur = getattr(node, "_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = getattr(cur, "_parent", None)
        return ".".join(reversed(parts)) or "<module>"

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def enclosing_function(self, node: ast.AST):
        cur = getattr(node, "_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_parent", None)
        return None

    def ancestors(self, node: ast.AST):
        cur = getattr(node, "_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_parent", None)


def parse_module(relpath: str, source: str) -> ModuleContext:
    tree = ast.parse(source)
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._parent = node  # type: ignore[attr-defined]
    annotations: dict[int, dict[str, str]] = {}
    guards: dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ANN_RE.search(tok.string)
            if m:
                annotations.setdefault(tok.start[0], {})[m.group(1)] = (
                    m.group(2).strip()
                )
            g = _GUARD_RE.search(tok.string)
            if g:
                guards[tok.start[0]] = g.group(1)
    except tokenize.TokenError:
        pass
    return ModuleContext(
        relpath=relpath,
        source=source,
        tree=tree,
        lines=source.splitlines(),
        annotations=annotations,
        guards=guards,
    )


# --- small node predicates shared across rules ---

def call_name(node: ast.Call) -> str:
    """Trailing name of the called thing: `np.copy(...)` -> "copy",
    `bytes(...)` -> "bytes"."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted rendering: `np.copy` -> "np.copy",
    `self._mu` -> "self._mu"; "" for non-name expressions."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def receiver_of(node: ast.Call) -> ast.AST | None:
    if isinstance(node.func, ast.Attribute):
        return node.func.value
    return None


def stmt_of(ctx: ModuleContext, node: ast.AST) -> ast.stmt | None:
    """Nearest enclosing statement node."""
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = getattr(cur, "_parent", None)
    return cur


def body_and_index(stmt: ast.stmt) -> tuple[list | None, int]:
    """(containing body list, index of stmt in it) — for next-sibling
    lookups in the pool-pairing rule."""
    parent = getattr(stmt, "_parent", None)
    if parent is None:
        return None, -1
    for fieldname in ("body", "orelse", "finalbody"):
        body = getattr(parent, fieldname, None)
        if isinstance(body, list) and stmt in body:
            return body, body.index(stmt)
    return None, -1
