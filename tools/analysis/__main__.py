"""``python -m tools.analysis`` — run the full scan, print the JSON
report to stdout, exit non-zero when any finding is not pinned in
baseline.json.

Options:
  --write-baseline   accept every current finding into baseline.json
                     (prints the report for the PRE-acceptance state)
  --no-baseline      raw scan: report everything as new, exit by it
  --all-rules        apply every rule to every file (ignore scopes)
  --quiet            print only the summary counts line
  [paths...]         restrict the scan to these repo-relative files
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import load_baseline, run, write_baseline


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analysis")
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--all-rules", action="store_true")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    baseline = {} if args.no_baseline else load_baseline()
    report = run(
        paths=args.paths or None,
        force_all_rules=args.all_rules,
        baseline=baseline,
    )
    if args.write_baseline:
        n = write_baseline(report)
        print(f"baseline: pinned {n} finding(s)", file=sys.stderr)
    if args.quiet:
        d = report.to_dict()
        print(json.dumps({"counts": d["counts"],
                          "wall_time_s": d["wall_time_s"]}))
    else:
        print(json.dumps(report.to_dict(), indent=2))
    if report.parse_errors:
        return 2
    return 0 if (args.write_baseline or not report.new) else 1


if __name__ == "__main__":
    sys.exit(main())
