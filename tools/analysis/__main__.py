"""``python -m tools.analysis`` — run the full scan, print the JSON
report to stdout, exit non-zero when any finding is not pinned in
baseline.json.

Options:
  --write-baseline   accept every current finding into baseline.json
                     (prints the report for the PRE-acceptance state)
  --no-baseline      raw scan: report everything as new, exit by it
  --all-rules        apply every rule to every file (ignore scopes)
  --rule NAME        run only this rule (repeatable)
  --since REV        scan only files changed since the git rev
                     (plus uncommitted changes) — local iteration mode
  --jobs N           worker processes (default: auto — cpu_count for
                     full scans, serial for small file lists)
  --json             full JSON report (the default; wins over --quiet)
  --quiet            print only the summary counts line
  [paths...]         restrict the scan to these repo-relative files
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import changed_since, load_baseline, run, write_baseline


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analysis")
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--all-rules", action="store_true")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="NAME")
    ap.add_argument("--since", default=None, metavar="REV")
    ap.add_argument("--jobs", type=int, default=None, metavar="N")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    paths = args.paths or None
    # A partial scan (restricted files or rules) must never rewrite
    # the baseline: write_baseline() pins EXACTLY the report's
    # findings, so accepting a partial report would silently drop
    # every waiver the scan did not cover.
    if args.write_baseline and (args.since or args.rule or paths):
        print("--write-baseline requires a full scan (no --since, "
              "--rule, or explicit paths)", file=sys.stderr)
        return 2
    if args.since is not None:
        if paths:
            print("--since and explicit paths are mutually exclusive",
                  file=sys.stderr)
            return 2
        try:
            paths = changed_since(args.since)
        except RuntimeError as exc:  # bad rev: usage error, not findings
            print(str(exc), file=sys.stderr)
            return 2
        if not paths:
            print(json.dumps({"counts": {"total": 0, "new": 0,
                                         "waived": 0},
                              "wall_time_s": 0.0,
                              "since": args.since,
                              "files_scanned": 0}))
            return 0

    baseline = {} if args.no_baseline else load_baseline()
    try:
        report = run(
            paths=paths,
            force_all_rules=args.all_rules,
            baseline=baseline,
            rules=args.rule,
            jobs=args.jobs,
        )
    except ValueError as exc:  # unknown --rule name
        print(str(exc), file=sys.stderr)
        return 2
    if args.write_baseline:
        n = write_baseline(report)
        print(f"baseline: pinned {n} finding(s)", file=sys.stderr)
    if args.quiet and not args.json:
        d = report.to_dict()
        print(json.dumps({"counts": d["counts"],
                          "wall_time_s": d["wall_time_s"]}))
    else:
        print(json.dumps(report.to_dict(), indent=2))
    if report.parse_errors:
        return 2
    return 0 if (args.write_baseline or not report.new) else 1


if __name__ == "__main__":
    sys.exit(main())
