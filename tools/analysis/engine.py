"""Analysis engine: file discovery, rule dispatch, baseline matching,
JSON report.

Findings are identified by a content fingerprint — (rule, path,
enclosing scope, normalized source line) — NOT by line number, so an
unrelated edit above a waived site does not resurrect it. The baseline
(tools/analysis/baseline.json) pins accepted pre-existing findings;
anything not in it is NEW and fails the tier-1 gate
(tests/test_static_analysis.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field

from . import astutil

# Scanned tree: the package, the analysis tooling itself (self-check),
# and the top-level drivers. tests/ stays out — fixture files contain
# deliberate violations.
SCAN_ROOTS = ("minio_tpu", "tools")
SCAN_FILES = ("bench.py", "__graft_entry__.py")

BASELINE_NAME = "baseline.json"


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    scope: str
    message: str
    snippet: str
    # "baseline" or "" — annotation-waived sites never become findings.
    waived_by: str = ""
    # Ordinal among same-(rule,scope,snippet) findings in this file,
    # assigned by run(): a copy-pasted second occurrence of a waived
    # line fingerprints differently and stays NEW.
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        raw = (f"{self.rule}|{self.path}|{self.scope}|{self.snippet}"
               f"|{self.occurrence}")
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "scope": self.scope,
            "message": self.message,
            "snippet": self.snippet,
            "occurrence": self.occurrence,
            "fingerprint": self.fingerprint,
            "waived_by": self.waived_by,
        }


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: list[dict] = field(default_factory=list)
    wall_time_s: float = 0.0
    baseline_size: int = 0

    @property
    def new(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived_by]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived_by]

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "wall_time_s": round(self.wall_time_s, 3),
            "baseline_size": self.baseline_size,
            "counts": {
                "total": len(self.findings),
                "new": len(self.new),
                "waived": len(self.waived),
            },
            "by_rule": self._by_rule(),
            "new_findings": [f.to_dict() for f in self.new],
            "waived_findings": [f.to_dict() for f in self.waived],
            "parse_errors": self.parse_errors,
        }

    def _by_rule(self) -> dict:
        out: dict[str, dict] = {}
        for f in self.findings:
            d = out.setdefault(f.rule, {"new": 0, "waived": 0})
            d["waived" if f.waived_by else "new"] += 1
        return out


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        BASELINE_NAME)


def all_rules(names: list[str] | None = None) -> list:
    from . import (
        copy_lint,
        except_lint,
        guardedby_lint,
        jax_lint,
        knob_lint,
        lifetime_lint,
        lock_lint,
        metrics_lint,
        pool_lint,
        shm_lint,
    )

    rules = [
        copy_lint.RULE,
        lock_lint.RULE,
        pool_lint.RULE,
        jax_lint.RULE,
        except_lint.RULE,
        metrics_lint.RULE,
        lifetime_lint.RULE,
        shm_lint.RULE,
        guardedby_lint.RULE,
        knob_lint.RULE,
    ]
    if names is None:
        return rules
    wanted = set(names)
    picked = [r for r in rules if r.name in wanted]
    missing = wanted - {r.name for r in picked}
    if missing:
        raise ValueError(
            f"unknown rule(s) {sorted(missing)}; known: "
            f"{[r.name for r in rules]}"
        )
    return picked


def discover(root: str) -> list[str]:
    """Repo-relative paths of every scanned source file, sorted for
    stable report ordering."""
    out: list[str] = []
    for top in SCAN_ROOTS:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ))
    for fn in SCAN_FILES:
        if os.path.exists(os.path.join(root, fn)):
            out.append(fn)
    return sorted(out)


def load_baseline(path: str | None = None) -> dict[str, dict]:
    """fingerprint -> waiver entry. Missing file = empty baseline."""
    path = path or baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {w["fingerprint"]: w for w in data.get("waivers", [])}


def write_baseline(report: Report, path: str | None = None) -> int:
    """Pin every current finding (new and already-waived) as accepted.
    The waiver entry carries the human-readable site info so a reviewer
    can audit baseline.json without re-running the scan."""
    path = path or baseline_path()
    waivers = [
        {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "scope": f.scope,
            "snippet": f.snippet,
            "message": f.message,
        }
        for f in sorted(report.findings,
                        key=lambda f: (f.rule, f.path, f.line))
    ]
    with open(path, "w") as fh:
        json.dump({"version": 1, "waivers": waivers}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return len(waivers)


def _scan_one(root: str, rel: str, rules: list,
              force_all_rules: bool) -> tuple[bool, list, dict | None]:
    """Scan one file: (scanned?, findings with per-file occurrence
    ordinals assigned, parse-error entry or None)."""
    full = rel if os.path.isabs(rel) else os.path.join(root, rel)
    try:
        with open(full, encoding="utf-8") as f:
            source = f.read()
        ctx = astutil.parse_module(rel, source)
    except (OSError, SyntaxError, ValueError) as exc:
        return False, [], {"path": rel, "error": str(exc)}
    file_findings: list[Finding] = []
    for rule in rules:
        if not force_all_rules and not rule.applies(rel):
            continue
        file_findings.extend(rule.check(ctx))
    # Disambiguate identical (rule, scope, snippet) findings by
    # source order before baseline matching, so one waiver covers
    # exactly one site.
    out: list[Finding] = []
    seen: dict[tuple, int] = {}
    for finding in sorted(file_findings,
                          key=lambda f: (f.line, f.col, f.rule)):
        key = (finding.rule, finding.scope, finding.snippet)
        finding.occurrence = seen.get(key, 0)
        seen[key] = finding.occurrence + 1
        out.append(finding)
    return True, out, None


def _auto_jobs(n_files: int) -> int:
    """Files-per-worker parallelism: one worker interpreter is worth
    ~0.15 s of startup, so parallelize only when the serial scan
    clearly dwarfs that (the full-repo scan; not a 3-file --since
    pass)."""
    cpus = os.cpu_count() or 1
    if cpus < 2 or n_files < 32:
        return 1
    return min(cpus, max(2, n_files // 16))


def _chunk_cli() -> None:
    """Child-process entry for the parallel scan: JSON task on stdin
    ({root, paths, force_all_rules, rules}), JSON result on stdout.
    A plain subprocess (not multiprocessing spawn) so the parent's
    __main__ — pytest, bench — is never re-executed (same reasoning
    as pipeline/workers)."""
    import sys

    task = json.load(sys.stdin)
    rules = all_rules(task.get("rules"))
    findings: list[dict] = []
    errors: list[dict] = []
    scanned = 0
    for rel in task["paths"]:
        ok, file_findings, err = _scan_one(
            task["root"], rel, rules, task["force_all_rules"]
        )
        if ok:
            scanned += 1
        if err is not None:
            errors.append(err)
        findings.extend(f.to_dict() for f in file_findings)
    json.dump({"scanned": scanned, "findings": findings,
               "errors": errors}, sys.stdout)


def _finding_from_dict(d: dict) -> Finding:
    return Finding(
        rule=d["rule"], path=d["path"], line=d["line"], col=d["col"],
        scope=d["scope"], message=d["message"], snippet=d["snippet"],
        occurrence=d.get("occurrence", 0),
    )


def _scan_parallel(root: str, rel_paths: list[str], jobs: int,
                   force_all_rules: bool,
                   rule_names: list[str] | None,
                   report: Report) -> list[Finding]:
    """Fan the file list across `jobs` child interpreters; falls back
    to an in-process scan for any chunk whose child fails, so a
    sandboxed host degrades to the serial result, never to a partial
    report."""
    import subprocess
    import sys

    chunks = [rel_paths[i::jobs] for i in range(jobs)]
    chunks = [c for c in chunks if c]
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for chunk in chunks:
        task = json.dumps({
            "root": root, "paths": chunk,
            "force_all_rules": force_all_rules, "rules": rule_names,
        })
        p = None
        try:
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "from tools.analysis.engine import _chunk_cli; "
                 "_chunk_cli()"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                env=env, cwd=root, text=True,
            )
            p.stdin.write(task)
            p.stdin.close()
        except OSError:
            # Spawn or handoff failed. A child that DID start must be
            # reaped here (the fallback path never waits on it) or it
            # zombies for the parent's lifetime.
            if p is not None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                p = None
        procs.append((chunk, p))
    rules = None
    findings: list[Finding] = []
    for chunk, p in procs:
        payload = None
        if p is not None:
            # Not communicate(): stdin is already closed (the children
            # all started before this drain loop), and communicate()
            # insists on flushing it.
            out = p.stdout.read()
            rc = p.wait()
            if rc == 0 and out:
                try:
                    payload = json.loads(out)
                except ValueError:
                    payload = None
        if payload is None:
            # Child failed (sandbox, OOM, crash): scan this chunk
            # here instead.
            if rules is None:
                rules = all_rules(rule_names)
            for rel in chunk:
                ok, file_findings, err = _scan_one(
                    root, rel, rules, force_all_rules
                )
                if ok:
                    report.files_scanned += 1
                if err is not None:
                    report.parse_errors.append(err)
                findings.extend(file_findings)
            continue
        report.files_scanned += payload["scanned"]
        report.parse_errors.extend(payload["errors"])
        findings.extend(_finding_from_dict(d)
                        for d in payload["findings"])
    return findings


def changed_since(rev: str, root: str | None = None) -> list[str]:
    """Repo-relative .py paths changed since `rev` — tracked diffs
    PLUS untracked files (a brand-new module is exactly what local
    iteration is editing) — the --since incremental mode's filter."""
    import subprocess

    root = root or repo_root()
    out = subprocess.run(
        ["git", "diff", "--name-only", rev, "--", "*.py"],
        capture_output=True, text=True, cwd=root, timeout=30,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {rev} failed: {out.stderr.strip()}"
        )
    changed = {ln.strip() for ln in out.stdout.splitlines()
               if ln.strip()}
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "--",
         "*.py"],
        capture_output=True, text=True, cwd=root, timeout=30,
    )
    if untracked.returncode == 0:
        changed.update(ln.strip() for ln in untracked.stdout.splitlines()
                       if ln.strip())
    return [p for p in discover(root) if p in changed]


def run(root: str | None = None, paths: list[str] | None = None,
        force_all_rules: bool = False,
        baseline: dict | None = None,
        use_baseline: bool = True,
        rules: list[str] | None = None,
        jobs: int | None = None) -> Report:
    """Scan and return the Report.

    root            repo root (auto-detected by default)
    paths           explicit repo-relative (or absolute) file list;
                    default = full repo scan
    force_all_rules apply every rule to every file regardless of its
                    scope filter (the fixture harness uses this)
    baseline        fingerprint->entry map; None loads baseline.json
                    (pass use_baseline=False for a raw scan)
    rules           restrict to these rule names (None = all)
    jobs            worker processes for the scan; None auto-sizes to
                    os.cpu_count() for full-repo scans and stays
                    serial for small file lists, 1 forces serial
    """
    t0 = time.perf_counter()
    root = root or repo_root()
    if baseline is None and use_baseline:
        baseline = load_baseline()
    baseline = baseline or {}
    rel_paths = paths if paths is not None else discover(root)
    if jobs is None:
        jobs = _auto_jobs(len(rel_paths))
    # Validate rule names HERE, not in the workers: an unknown --rule
    # must be one ValueError, not N crashed child interpreters.
    rule_objs = all_rules(rules)

    report = Report(baseline_size=len(baseline))
    if jobs > 1:
        findings = _scan_parallel(root, rel_paths, jobs,
                                  force_all_rules, rules, report)
    else:
        findings = []
        for rel in rel_paths:
            ok, file_findings, err = _scan_one(root, rel, rule_objs,
                                               force_all_rules)
            if ok:
                report.files_scanned += 1
            if err is not None:
                report.parse_errors.append(err)
            findings.extend(file_findings)
    for finding in findings:
        if finding.fingerprint in baseline:
            finding.waived_by = "baseline"
        report.findings.append(finding)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    report.wall_time_s = time.perf_counter() - t0
    return report
