"""Analysis engine: file discovery, rule dispatch, baseline matching,
JSON report.

Findings are identified by a content fingerprint — (rule, path,
enclosing scope, normalized source line) — NOT by line number, so an
unrelated edit above a waived site does not resurrect it. The baseline
(tools/analysis/baseline.json) pins accepted pre-existing findings;
anything not in it is NEW and fails the tier-1 gate
(tests/test_static_analysis.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field

from . import astutil

# Scanned tree: the package, the analysis tooling itself (self-check),
# and the top-level drivers. tests/ stays out — fixture files contain
# deliberate violations.
SCAN_ROOTS = ("minio_tpu", "tools")
SCAN_FILES = ("bench.py", "__graft_entry__.py")

BASELINE_NAME = "baseline.json"


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    scope: str
    message: str
    snippet: str
    # "baseline" or "" — annotation-waived sites never become findings.
    waived_by: str = ""
    # Ordinal among same-(rule,scope,snippet) findings in this file,
    # assigned by run(): a copy-pasted second occurrence of a waived
    # line fingerprints differently and stays NEW.
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        raw = (f"{self.rule}|{self.path}|{self.scope}|{self.snippet}"
               f"|{self.occurrence}")
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "scope": self.scope,
            "message": self.message,
            "snippet": self.snippet,
            "occurrence": self.occurrence,
            "fingerprint": self.fingerprint,
            "waived_by": self.waived_by,
        }


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: list[dict] = field(default_factory=list)
    wall_time_s: float = 0.0
    baseline_size: int = 0

    @property
    def new(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived_by]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived_by]

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "wall_time_s": round(self.wall_time_s, 3),
            "baseline_size": self.baseline_size,
            "counts": {
                "total": len(self.findings),
                "new": len(self.new),
                "waived": len(self.waived),
            },
            "by_rule": self._by_rule(),
            "new_findings": [f.to_dict() for f in self.new],
            "waived_findings": [f.to_dict() for f in self.waived],
            "parse_errors": self.parse_errors,
        }

    def _by_rule(self) -> dict:
        out: dict[str, dict] = {}
        for f in self.findings:
            d = out.setdefault(f.rule, {"new": 0, "waived": 0})
            d["waived" if f.waived_by else "new"] += 1
        return out


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        BASELINE_NAME)


def all_rules() -> list:
    from . import (
        copy_lint,
        except_lint,
        jax_lint,
        lock_lint,
        metrics_lint,
        pool_lint,
    )

    return [
        copy_lint.RULE,
        lock_lint.RULE,
        pool_lint.RULE,
        jax_lint.RULE,
        except_lint.RULE,
        metrics_lint.RULE,
    ]


def discover(root: str) -> list[str]:
    """Repo-relative paths of every scanned source file, sorted for
    stable report ordering."""
    out: list[str] = []
    for top in SCAN_ROOTS:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ))
    for fn in SCAN_FILES:
        if os.path.exists(os.path.join(root, fn)):
            out.append(fn)
    return sorted(out)


def load_baseline(path: str | None = None) -> dict[str, dict]:
    """fingerprint -> waiver entry. Missing file = empty baseline."""
    path = path or baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {w["fingerprint"]: w for w in data.get("waivers", [])}


def write_baseline(report: Report, path: str | None = None) -> int:
    """Pin every current finding (new and already-waived) as accepted.
    The waiver entry carries the human-readable site info so a reviewer
    can audit baseline.json without re-running the scan."""
    path = path or baseline_path()
    waivers = [
        {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "scope": f.scope,
            "snippet": f.snippet,
            "message": f.message,
        }
        for f in sorted(report.findings,
                        key=lambda f: (f.rule, f.path, f.line))
    ]
    with open(path, "w") as fh:
        json.dump({"version": 1, "waivers": waivers}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return len(waivers)


def run(root: str | None = None, paths: list[str] | None = None,
        force_all_rules: bool = False,
        baseline: dict | None = None,
        use_baseline: bool = True) -> Report:
    """Scan and return the Report.

    root            repo root (auto-detected by default)
    paths           explicit repo-relative (or absolute) file list;
                    default = full repo scan
    force_all_rules apply every rule to every file regardless of its
                    scope filter (the fixture harness uses this)
    baseline        fingerprint->entry map; None loads baseline.json
                    (pass use_baseline=False for a raw scan)
    """
    t0 = time.perf_counter()
    root = root or repo_root()
    rules = all_rules()
    if baseline is None and use_baseline:
        baseline = load_baseline()
    baseline = baseline or {}
    rel_paths = paths if paths is not None else discover(root)

    report = Report(baseline_size=len(baseline))
    for rel in rel_paths:
        full = rel if os.path.isabs(rel) else os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as f:
                source = f.read()
            ctx = astutil.parse_module(rel, source)
        except (OSError, SyntaxError, ValueError) as exc:
            report.parse_errors.append({"path": rel, "error": str(exc)})
            continue
        report.files_scanned += 1
        file_findings: list[Finding] = []
        for rule in rules:
            if not force_all_rules and not rule.applies(rel):
                continue
            file_findings.extend(rule.check(ctx))
        # Disambiguate identical (rule, scope, snippet) findings by
        # source order before baseline matching, so one waiver covers
        # exactly one site.
        seen: dict[tuple, int] = {}
        for finding in sorted(file_findings,
                              key=lambda f: (f.line, f.col, f.rule)):
            key = (finding.rule, finding.scope, finding.snippet)
            finding.occurrence = seen.get(key, 0)
            seen[key] = finding.occurrence + 1
            if finding.fingerprint in baseline:
                finding.waived_by = "baseline"
            report.findings.append(finding)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    report.wall_time_s = time.perf_counter() - t0
    return report
