"""copy-lint: unaccounted data copies in the erasure hot path.

PR 3 stripped the PUT path to exactly one copy per payload byte and
pinned it with CopyCounters; this rule keeps the next change honest.
In the hot-path modules it flags the copy-producing constructs —
``bytes(x)``, ``.tobytes()``, ``np.copy`` / ``.copy()``,
``ascontiguousarray``, and slices of bytes-typed locals (bytes slicing
copies; ndarray slicing does not) — unless the site carries a
``# copy-ok: <site>`` annotation.

The annotation label is validated: it must either name a CopyCounters
site that a ``copy_add("<site>", ...)`` call in the same module
actually feeds, or be the literal ``meta`` (bounded non-payload bytes:
digests, error paths, metadata packs — document the judgment in
docs/ANALYSIS.md). An annotation whose label is neither is itself a
finding, so a stale label cannot silently un-count a copy.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .engine import Finding

KEY = "copy"

HOT_PATHS = {
    "minio_tpu/erasure/streaming.py",
    "minio_tpu/erasure/device_engine.py",
    "minio_tpu/parallel/mesh_engine.py",
    "minio_tpu/storage/local.py",
    # Added since PR6 (ISSUE 13): the worker read ops move payload
    # through shm views, the admission/span planes sit ON the request
    # path — a stray materialization there taxes every stream.
    "minio_tpu/pipeline/workers.py",
    "minio_tpu/pipeline/admission.py",
    "minio_tpu/observability/spans.py",
    # Added with ISSUE 15: the soak engine moves client payloads; a
    # stray materialization there skews the throughput-floor numbers
    # the gate enforces.
    "minio_tpu/faults/scenarios.py",
    # Added with ISSUE 16: codec selection/probing sits on every PUT's
    # setup path (ops/cauchy.py rides the existing ops/ prefix).
    "minio_tpu/erasure/registry.py",
    # Added with ISSUE 19: the hot-object tier sits on the GET hot
    # path; its ONE sanctioned retained copy (decoded blocks leaving
    # the recycled reader ring) is budgeted as get.cache_hold — any
    # other materialization there taxes every hot GET.
    "minio_tpu/object/readtier.py",
}
HOT_PREFIXES = ("minio_tpu/ops/",)

# Labels exempt from copy_add routing: bounded, non-payload bytes.
META_LABEL = "meta"

_COPY_CALLS = {"tobytes", "ascontiguousarray"}


class CopyLint:
    name = "copy-lint"

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        return rel in HOT_PATHS or rel.startswith(HOT_PREFIXES)

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        routed = _copy_add_labels(ctx)
        # Validate annotations first: every copy-ok label must be
        # routed through CopyCounters (or be the documented 'meta').
        for lineno, anns in sorted(ctx.annotations.items()):
            reason = anns.get(KEY)
            if reason is None:
                continue
            # The label is the first token; anything after it is
            # free-form commentary ("# copy-ok: put.tail_copy — why").
            label = reason.split()[0]
            if label != META_LABEL and label not in routed:
                yield Finding(
                    rule=self.name, path=ctx.relpath, line=lineno, col=0,
                    scope="<annotation>",
                    message=(
                        f"copy-ok label '{label}' is not fed by any "
                        f"copy_add() in this module — route the copy "
                        f"through pipeline/buffers.CopyCounters or use "
                        f"'meta' for bounded non-payload bytes"
                    ),
                    snippet=ctx.line_text(lineno),
                )
        bytes_locals = _bytes_typed_locals(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                msg = self._copy_call(node)
                if msg and ctx.annotation(KEY, node.lineno) is None:
                    yield self._finding(ctx, node, msg)
            elif isinstance(node, ast.Subscript):
                msg = self._bytes_slice(ctx, node, bytes_locals)
                if msg and ctx.annotation(KEY, node.lineno) is None:
                    yield self._finding(ctx, node, msg)

    def _copy_call(self, node: ast.Call) -> str | None:
        name = astutil.call_name(node)
        dotted = astutil.dotted_name(node.func)
        if name == "bytes" and isinstance(node.func, ast.Name) \
                and node.args:
            return "bytes(...) materializes a full copy"
        if name in _COPY_CALLS:
            return f"{name}() materializes a full copy"
        if name == "copy" and dotted.startswith(("np.", "numpy.")):
            return "np.copy() materializes a full copy"
        if name == "copy" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            return ".copy() materializes a full copy"
        return None

    def _bytes_slice(self, ctx, node: ast.Subscript,
                     bytes_locals: dict) -> str | None:
        if not isinstance(node.slice, ast.Slice):
            return None
        if not isinstance(node.ctx, ast.Load):
            return None
        if not isinstance(node.value, ast.Name):
            return None
        fn = ctx.enclosing_function(node)
        names = bytes_locals.get(id(fn), set())
        if node.value.id in names:
            return (
                f"slicing bytes local '{node.value.id}' copies the "
                f"slice (use a memoryview)"
            )
        return None

    def _finding(self, ctx, node, msg) -> Finding:
        return Finding(
            rule=self.name, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=ctx.scope_of(node),
            message=msg, snippet=ctx.line_text(node.lineno),
        )


def _copy_add_labels(ctx: astutil.ModuleContext) -> set[str]:
    """String labels fed to copy_add(...) / COPY.add(...) /
    ascontig_counted(_, label) anywhere in the module — the set a
    copy-ok annotation may legitimately name."""
    labels: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name not in ("copy_add", "add", "ascontig_counted"):
            continue
        if name == "add":
            dotted = astutil.dotted_name(node.func)
            if not dotted.endswith("COPY.add"):
                continue
        label_arg = 1 if name == "ascontig_counted" else 0
        if len(node.args) > label_arg \
                and isinstance(node.args[label_arg], ast.Constant) \
                and isinstance(node.args[label_arg].value, str):
            labels.add(node.args[label_arg].value)
    return labels


def _bytes_typed_locals(ctx: astutil.ModuleContext) -> dict:
    """Per-function names provably bound to bytes: assigned from
    ``.read(...)``, ``.tobytes()``, ``bytes(...)`` or a bytes literal.
    Intra-function, flow-insensitive — deliberately narrow so the slice
    sub-rule has no false positives on ndarray views."""
    out: dict[int, set[str]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Name):
            continue
        val = node.value
        is_bytes = False
        if isinstance(val, ast.Constant) and isinstance(val.value, bytes):
            is_bytes = True
        elif isinstance(val, ast.Call):
            cname = astutil.call_name(val)
            if cname in ("read", "tobytes", "bytes"):
                is_bytes = True
        if is_bytes:
            fn = ctx.enclosing_function(node)
            out.setdefault(id(fn), set()).add(node.targets[0].id)
    return out


RULE = CopyLint()
