"""lifetime-lint: pooled-buffer lifetime verification (dataflow).

The hazard class PR8's deferred-release handshake exists for: a
recycled strip/ring segment scribbled by someone still holding a view
of it. pool-lint proves a checkout has *a* release; this rule proves
the release is at the right POINT in the flow. Four sub-rules, all
driven by the shared dataflow engine (def-use chains + alias tracking
through assignments, views, tuple packing and closures):

- **use-after-release** — any read of a name aliasing a pooled buffer
  after a statement that (may have) released it back to its pool.
  The next acquirer owns those bytes now.
- **double-release** — releasing the same checkout twice corrupts the
  pool's accounting and freelists one buffer under two owners.
- **return-past-release** — ``return`` of a view derived from a pooled
  buffer that an enclosing ``finally`` releases: the finally runs
  before the caller sees the value, so the caller receives a recycled
  buffer. (``yield`` is exempt — the generator's finally runs at
  close, after the consumer drained the view; that is the documented
  streaming-ring idiom.)
- **handoff-release** — a buffer view handed to another thread
  (``executor.submit``, ``threading.Thread``, ``Pipeline``/``Stage``
  closures — directly as an argument or captured free in a closure)
  and then released while that thread may still be running. Silent
  when the handoff was joined first (``.join()`` / ``.result()`` /
  ``.wait()`` on the handle) or when the release is guarded by an
  in-flight handshake (the release statement sits under an ``if``
  whose test reads an ``inflight``-named gate — the PR8
  deferred-release shape in erasure/bitrot.py).

A checkout is ``<pool>.acquire()`` with a pool-ish receiver (same
structural test as pool-lint). Releases: ``<pool>.release(x)``,
``x.release_buffers()``, ``x.close()``. Stores into attributes or
subscripts escape the intra-procedural frame and end tracking (the
object graph owns the buffer now; the runtime ``in_use == 0`` sweeps
cover that side). Waive deliberate sites with
``# lifetime-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil, dataflow
from .engine import Finding
from .pool_lint import _pool_assigned_names

KEY = "lifetime"

_RELEASE_POOL_METHODS = {"release", "drop", "_release"}
_RELEASE_SELF_METHODS = {"release_buffers", "close"}
_HANDOFF_CALLS = {"submit", "apply_async"}
_HANDOFF_CTORS = {"Thread", "Stage", "Pipeline"}
_JOIN_METHODS = {"join", "result", "wait", "shutdown"}


class _Handoff:
    __slots__ = ("origins", "handle", "line", "joined")

    def __init__(self, origins: frozenset, handle: str | None, line: int):
        self.origins = origins
        self.handle = handle
        self.line = line
        self.joined = False


class _LifetimeState(dataflow.State):
    __slots__ = ("env", "released", "handoffs")

    def __init__(self):
        super().__init__()
        # name -> frozenset of origin keys (acquire-site line numbers)
        self.env: dict[str, frozenset] = {}
        # origin -> line of the (may-)release
        self.released: dict[int, int] = {}
        self.handoffs: list[_Handoff] = []

    def copy(self) -> "_LifetimeState":
        s = _LifetimeState()
        s.env = dict(self.env)
        s.released = dict(self.released)
        # Handoff records are shared identity on purpose: a join on
        # one path marks the same record every fork sees.
        s.handoffs = list(self.handoffs)
        s.dead = self.dead
        return s

    def merge_from(self, other: "_LifetimeState") -> None:
        for name, origins in other.env.items():
            self.env[name] = self.env.get(name, frozenset()) | origins
        for origin, line in other.released.items():
            self.released.setdefault(origin, line)
        seen = {id(h) for h in self.handoffs}
        self.handoffs.extend(h for h in other.handoffs
                             if id(h) not in seen)


class _FnScan(dataflow.FlowWalker):
    """One function's lifetime interpretation."""

    def __init__(self, ctx: astutil.ModuleContext, pool_names: set[str],
                 findings: list):
        super().__init__(ctx)
        self.pool_names = pool_names
        self.findings = findings
        self._seen: set[tuple] = set()  # dedupe across two-pass loops

    # -- helpers ------------------------------------------------------------

    def _is_pool_recv(self, recv: ast.AST) -> bool:
        name = astutil.dotted_name(recv)
        leaf = name.rsplit(".", 1)[-1]
        return ("pool" in leaf.lower() or leaf in self.pool_names)

    def _acquire_origin(self, expr: ast.AST) -> int | None:
        """Origin key when `expr` is `<pool>.acquire(...)`."""
        if (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "acquire"
                and self._is_pool_recv(expr.func.value)):
            return expr.lineno * 1000 + expr.col_offset
        return None

    def _emit(self, node, kind: str, message: str) -> None:
        key = (kind, node.lineno, message)
        if key in self._seen:
            return
        self._seen.add(key)
        if self.ctx.annotation(KEY, node.lineno) is not None:
            return
        self.findings.append(Finding(
            rule="lifetime-lint", path=self.ctx.relpath,
            line=node.lineno, col=getattr(node, "col_offset", 0),
            scope=self.ctx.scope_of(node), message=message,
            snippet=self.ctx.line_text(node.lineno),
        ))

    def _release_targets(self, call: ast.Call,
                         state: _LifetimeState) -> frozenset:
        """Origins a call releases, or an empty set."""
        if not isinstance(call.func, ast.Attribute):
            return frozenset()
        attr = call.func.attr
        if attr in _RELEASE_POOL_METHODS and call.args \
                and self._is_pool_recv(call.func.value):
            return dataflow.origins_of(call.args[0], state.env)
        if attr in _RELEASE_SELF_METHODS and not call.args:
            return dataflow.origins_of(call.func.value, state.env)
        return frozenset()

    @staticmethod
    def _inflight_guarded(ctx, node) -> bool:
        """True when `node` sits under an ``if`` whose test reads an
        inflight-style gate — the deferred-release handshake shape
        (``if self._inflight == 0: self._release_now()``)."""
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(anc, ast.If):
                for name in dataflow.names_in(anc.test):
                    if "inflight" in name.lower() \
                            or "in_flight" in name.lower():
                        return True
                for sub in ast.walk(anc.test):
                    if isinstance(sub, ast.Attribute) and (
                            "inflight" in sub.attr.lower()
                            or "in_flight" in sub.attr.lower()):
                        return True
        return False

    # -- transfer hooks ------------------------------------------------------

    def on_stmt(self, stmt, state: _LifetimeState) -> None:
        # Expression-position work: uses, releases, handoffs, joins.
        for expr in dataflow.stmt_exprs(stmt):
            self._scan_expr(expr, stmt, state)
        # Loop targets bind views of the iterated collection.
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            origins = dataflow.origins_of(stmt.iter, state.env)
            for name_node in dataflow.assigned_names(stmt.target):
                if origins:
                    state.env[name_node.id] = origins
                else:
                    state.env.pop(name_node.id, None)

    def _scan_expr(self, expr, stmt, state: _LifetimeState) -> None:
        # Uses are checked against the state BEFORE this statement's
        # releases apply — `pool.release(buf)` must not flag its own
        # argument — so the walk is staged: collect releases, check
        # uses (excluding names inside release calls), then apply
        # joins/handoffs/releases.
        nodes = list(dataflow.walk_no_defs(expr))
        releases: list[tuple[ast.Call, frozenset]] = []
        release_calls: set[int] = set()
        release_names: set[int] = set()
        for node in nodes:
            if isinstance(node, ast.Call):
                released = self._release_targets(node, state)
                if released:
                    releases.append((node, released))
                    release_calls.add(id(node))
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name):
                            release_names.add(id(sub))
        for node in nodes:
            if isinstance(node, ast.Call):
                if id(node) not in release_calls:
                    self._handle_join(node, state)
                    self._handle_handoff(node, stmt, state)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and id(node) not in release_names:
                self._check_use(node, state)
        for call, released in releases:
            self._handle_release(call, released, state)

    def _check_use(self, node: ast.Name, state: _LifetimeState) -> None:
        origins = state.env.get(node.id)
        if not origins:
            return
        for origin in origins:
            line = state.released.get(origin)
            if line is not None:
                self._emit(
                    node, "uar",
                    f"'{node.id}' is used after the pooled buffer it "
                    f"aliases was released (release at line {line}) — "
                    f"the pool may have recycled it to another stream; "
                    f"restructure or waive with '# lifetime-ok: "
                    f"<reason>'",
                )
                return

    def _handle_release(self, call: ast.Call, released: frozenset,
                        state: _LifetimeState) -> None:
        for origin in released:
            prior = state.released.get(origin)
            if prior is not None and prior != call.lineno:
                self._emit(
                    call, "double",
                    f"double release of a pooled buffer (first "
                    f"released at line {prior}) — the freelist would "
                    f"hold one buffer under two owners",
                )
            # Live thread handoffs of this origin: release-before-join.
            for h in state.handoffs:
                if origin in h.origins and not h.joined \
                        and not self._inflight_guarded(self.ctx, call):
                    self._emit(
                        call, "handoff",
                        f"pooled buffer released while a view handed "
                        f"to a thread at line {h.line} may still be "
                        f"live — a parked thread can scribble the "
                        f"recycled segment; join the handoff first or "
                        f"gate the release on an in-flight handshake",
                    )
                    break
            state.released[origin] = call.lineno

    def _handle_join(self, call: ast.Call, state: _LifetimeState) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        if call.func.attr not in _JOIN_METHODS:
            return
        handle = astutil.dotted_name(call.func.value)
        if not handle:
            return
        for h in state.handoffs:
            if h.handle == handle:
                h.joined = True

    def _handle_handoff(self, call: ast.Call, stmt,
                        state: _LifetimeState) -> None:
        name = astutil.call_name(call)
        is_submit = (isinstance(call.func, ast.Attribute)
                     and name in _HANDOFF_CALLS)
        is_ctor = (isinstance(call.func, (ast.Name, ast.Attribute))
                   and name in _HANDOFF_CTORS)
        if not (is_submit or is_ctor):
            return
        origins: set = set()
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            origins.update(dataflow.origins_of(arg, state.env))
            # Closures: a lambda/def passed (or referenced by name)
            # captures views by free variable.
            fns = []
            if isinstance(arg, ast.Lambda):
                fns.append(arg)
            elif isinstance(arg, ast.Name):
                fn = self._local_defs.get(arg.id)
                if fn is not None:
                    fns.append(fn)
            for fn in fns:
                for free in dataflow.free_names_of_def(fn):
                    origins.update(state.env.get(free, ()))
        if not origins:
            return
        handle = None
        if isinstance(stmt, ast.Assign):
            names = dataflow.assigned_names(stmt.targets[0])
            if len(names) == 1:
                handle = names[0].id
        state.handoffs.append(
            _Handoff(frozenset(origins), handle, call.lineno)
        )

    def on_assign(self, stmt, state: _LifetimeState) -> None:
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value = stmt.value
            targets = [stmt.target]
        else:
            return  # AugAssign never rebinds to a fresh buffer
        # A subscript/attribute store INTO a tracked name mutates the
        # container: our knowledge of what it holds is stale, so its
        # aliasing ends (`item[0] = None` after a release is exactly
        # the nil-the-entry ownership protocol the executors use).
        for tgt in targets:
            if isinstance(tgt, (ast.Subscript, ast.Attribute)) \
                    and isinstance(tgt.value, ast.Name):
                state.env.pop(tgt.value.id, None)
        origin = self._acquire_origin(value)
        if origin is not None:
            origins: frozenset = frozenset((origin,))
            # A fresh checkout from this site starts a NEW lifetime:
            # the previous iteration's release belongs to the previous
            # buffer.
            state.released.pop(origin, None)
        else:
            origins = dataflow.origins_of(value, state.env)
        for name_node in dataflow.assigned_names(
                targets[0] if len(targets) == 1 else ast.Tuple(
                    elts=list(targets), ctx=ast.Store())):
            if origin is not None or origins:
                state.env[name_node.id] = origins
            else:
                state.env.pop(name_node.id, None)

    def on_return(self, stmt: ast.Return, state: _LifetimeState) -> None:
        if stmt.value is None:
            return
        origins = dataflow.origins_of(stmt.value, state.env)
        if not origins:
            return
        # Releases pending in enclosing finally blocks run AFTER the
        # return value is computed but BEFORE the caller receives it.
        pending: dict[int, int] = {}
        for finalbody in self.finally_stack:
            for node in ast.walk(ast.Module(body=list(finalbody),
                                            type_ignores=[])):
                if isinstance(node, ast.Call):
                    for o in self._release_targets(node, state):
                        pending.setdefault(o, node.lineno)
        for origin in origins:
            line = state.released.get(origin, pending.get(origin))
            if line is not None:
                self._emit(
                    stmt, "ret",
                    f"returning a view of a pooled buffer that is "
                    f"released before the caller can use it (release "
                    f"at line {line}) — the caller receives a "
                    f"recycled buffer",
                )
                return

    def on_nested_def(self, node, state) -> None:
        pass  # closures surface via _handle_handoff's free-name scan

    # populated by the rule before walking
    _local_defs: dict[str, ast.AST] = {}


class LifetimeLint:
    name = "lifetime-lint"

    def applies(self, relpath: str) -> bool:
        return True  # origins only arise from pool-ish acquires

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        pool_names = _pool_assigned_names(ctx)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scan = _FnScan(ctx, pool_names, findings)
            scan._local_defs = {
                sub.name: sub for sub in ast.walk(node)
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                and sub is not node
            }
            scan.walk_function(node, _LifetimeState())
        yield from findings


RULE = LifetimeLint()
