"""metrics-lint: every mtpu_*/span series written at runtime must have
a descriptor in the metrics_v2 catalog — and every catalog descriptor
must have a write site somewhere in the tree.

The registry (observability/metrics.py) happily creates a series for
ANY name it is handed — a typo'd `reg.inc("wroker_tasks_total")` ships
a new undocumented series and silently starves the real one, and a
series written without a catalog descriptor renders with no HELP text
and is invisible to the dashboards built off the descriptor list. This
rule closes the loop statically in BOTH directions:

- **write→catalog** — each registry write whose series name is a
  string literal (`.inc("...")`, `.observe("...")`, `.set_gauge`,
  `.inc_gauge`, `.set_counter`, `.time`) must name a series that
  appears in a `*DESCRIPTORS` catalog list somewhere under minio_tpu/.
- **catalog→write (dead-series)** — each `*DESCRIPTORS` entry must
  have SOME write evidence in the tree: a literal write call, an
  f-string write whose pattern matches the name, or the name appearing
  as a plain string constant outside any descriptor list (the
  table-driven mirror loops pass series names through tuples). A
  descriptor nothing writes is a dashboard lying about coverage.

The catalog and the write-site index are extracted from the SOURCE
(AST over every module), never by importing minio_tpu — the lint gate
must stay runnable on a tree whose imports are broken, which is
exactly when you want it most.

Dynamic names (f-strings, variables) cannot be write-checked and are
skipped; read-side helpers (`counter_value`, `gauge`) are reads, not
writes. A deliberate off-catalog write or an intentionally-reserved
descriptor takes `# metrics-ok: <reason>`.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from . import astutil
from .engine import Finding, repo_root

KEY = "metrics"

# Registry write methods whose first positional argument is the series
# name. `time` is observe's context-manager twin; `set_counter` is the
# scrape-time absolute mirror.
_WRITE_METHODS = {"inc", "observe", "set_gauge", "inc_gauge",
                  "set_counter", "replace_counter_series",
                  "replace_gauge_series", "time"}

# The registry implementation itself manipulates series generically
# (name is a parameter); it can never name a literal series.
_EXEMPT = {"minio_tpu/observability/metrics.py"}

# Files outside minio_tpu/ that legitimately write series (drivers).
_EXTRA_WRITE_FILES = ("bench.py", "__graft_entry__.py")


def _descriptor_lists(tree: ast.AST) -> list[ast.List]:
    """Every list literal assigned to a *DESCRIPTORS name."""
    out = []
    for node in ast.walk(tree):
        targets: list = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id.endswith("DESCRIPTORS")
            for t in targets
        ):
            continue
        value = getattr(node, "value", None)
        if isinstance(value, ast.List):
            out.append(value)
    return out


def _entries(desc_list: ast.List):
    """(name, line) for each (name, type, help) tuple literal."""
    for el in desc_list.elts:
        if (isinstance(el, ast.Tuple) and el.elts
                and isinstance(el.elts[0], ast.Constant)
                and isinstance(el.elts[0].value, str)):
            yield el.elts[0].value, el.lineno


class _Evidence:
    """Write-site evidence extracted from one module's AST."""

    __slots__ = ("literals", "patterns", "constants")

    def __init__(self):
        self.literals: set[str] = set()   # literal write first-args
        self.patterns: list = []          # compiled f-string regexes
        self.constants: set[str] = set()  # strings outside catalogs

    def update_from(self, tree: ast.AST) -> None:
        # Neither a catalog entry's own strings nor docstrings/bare
        # string statements are write evidence — a dead series whose
        # name is MENTIONED in module prose must still fire.
        skip_const_ids = set()
        for dl in _descriptor_lists(tree):
            for node in ast.walk(dl):
                if isinstance(node, ast.Constant):
                    skip_const_ids.add(id(node))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Constant)):
                skip_const_ids.add(id(node.value))
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant):
                if (isinstance(node.value, str)
                        and id(node) not in skip_const_ids):
                    self.constants.add(node.value)
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _WRITE_METHODS or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                self.literals.add(first.value)
            elif isinstance(first, ast.JoinedStr):
                parts = []
                for v in first.values:
                    if isinstance(v, ast.Constant):
                        parts.append(re.escape(str(v.value)))
                    else:
                        parts.append(".*")
                try:
                    self.patterns.append(
                        re.compile("^" + "".join(parts) + "$")
                    )
                except re.error:
                    pass

    def covers(self, name: str) -> bool:
        if name in self.literals or name in self.constants:
            return True
        return any(p.match(name) for p in self.patterns)


def _scan_tree() -> tuple[frozenset[str], _Evidence]:
    """One pass over the source tree: (catalog names, write evidence).
    Parsed from source so both survive broken imports."""
    root = repo_root()
    names: set[str] = set()
    ev = _Evidence()
    paths = [os.path.join(root, f) for f in _EXTRA_WRITE_FILES]
    base = os.path.join(root, "minio_tpu")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        paths.extend(os.path.join(dirpath, fn) for fn in filenames
                     if fn.endswith(".py"))
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError, ValueError):
            continue
        if path.startswith(base):
            for dl in _descriptor_lists(tree):
                for name, _line in _entries(dl):
                    names.add(name)
        ev.update_from(tree)
    return frozenset(names), ev


class MetricsLint:
    name = "metrics-lint"

    def __init__(self):
        self._catalog: frozenset[str] | None = None
        self._evidence: _Evidence | None = None

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        if rel in _EXEMPT:
            return False
        return rel.startswith("minio_tpu/") or rel == "bench.py"

    def _index(self) -> tuple[frozenset[str], _Evidence]:
        if self._catalog is None:
            self._catalog, self._evidence = _scan_tree()
        return self._catalog, self._evidence

    def catalog(self) -> frozenset[str]:
        return self._index()[0]

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        catalog, tree_ev = self._index()
        # A module-local *DESCRIPTORS list catalogs its series too (the
        # real catalog walk only covers minio_tpu/; fixtures and future
        # out-of-tree tooling self-contain theirs).
        desc_lists = _descriptor_lists(ctx.tree)
        local_names = {name for dl in desc_lists
                       for name, _line in _entries(dl)}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _WRITE_METHODS:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not isinstance(first, ast.Constant) \
                    or not isinstance(first.value, str):
                continue  # dynamic name: unverifiable statically
            series = first.value
            if series in catalog or series in local_names:
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue
            yield Finding(
                rule=self.name,
                path=ctx.relpath,
                line=node.lineno,
                col=node.col_offset,
                scope=ctx.scope_of(node),
                message=(
                    f"series {series!r} written via .{func.attr}() has "
                    "no descriptor in the metrics_v2 catalog — add a "
                    "(name, type, help) entry to a *DESCRIPTORS list "
                    "or annotate `# metrics-ok: <reason>`"
                ),
                snippet=ctx.line_text(node.lineno),
            )
        # --- dead-series: this module's catalog entries need a write
        # site SOMEWHERE (the tree index covers minio_tpu/, bench.py,
        # __graft_entry__.py; fixture modules self-contain theirs).
        if not desc_lists:
            return
        local_ev = _Evidence()
        local_ev.update_from(ctx.tree)
        for dl in desc_lists:
            for name, line in _entries(dl):
                if tree_ev.covers(name) or local_ev.covers(name):
                    continue
                if ctx.annotation(KEY, line) is not None:
                    continue
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=line,
                    col=dl.col_offset,
                    scope=ctx.scope_of(dl),
                    message=(
                        f"descriptor {name!r} has no registry write "
                        "site anywhere in the tree (dead series — the "
                        "catalog promises a metric nothing produces): "
                        "wire a write, prune the entry, or annotate "
                        "`# metrics-ok: <reason>`"
                    ),
                    snippet=ctx.line_text(line),
                )


RULE = MetricsLint()
