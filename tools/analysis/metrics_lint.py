"""metrics-lint: every mtpu_*/span series written at runtime must have
a descriptor in the metrics_v2 catalog.

The registry (observability/metrics.py) happily creates a series for
ANY name it is handed — a typo'd `reg.inc("wroker_tasks_total")` ships
a new undocumented series and silently starves the real one, and a
series written without a catalog descriptor renders with no HELP text
and is invisible to the dashboards built off the descriptor list. This
rule closes the loop statically: each registry write whose series name
is a string literal (`.inc("...")`, `.observe("...")`,
`.set_gauge("...")`, `.inc_gauge("...")`, `.time("...")`) must name a
series that appears in a `*DESCRIPTORS` catalog list somewhere under
minio_tpu/.

The catalog is extracted from the SOURCE (AST over every module's
`*DESCRIPTORS = [...]` assignments), never by importing minio_tpu —
the lint gate must stay runnable on a tree whose imports are broken,
which is exactly when you want it most.

Dynamic names (f-strings, variables) cannot be checked and are
skipped; read-side helpers (`counter_value`, `gauge`) are reads, not
writes. A deliberate off-catalog write takes `# metrics-ok: <reason>`.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from . import astutil
from .engine import Finding, repo_root

KEY = "metrics"

# Registry write methods whose first positional argument is the series
# name. `time` is observe's context-manager twin.
_WRITE_METHODS = {"inc", "observe", "set_gauge", "inc_gauge", "time"}

# The registry implementation itself manipulates series generically
# (name is a parameter); it can never name a literal series.
_EXEMPT = {"minio_tpu/observability/metrics.py"}


def _catalog_names(root: str) -> frozenset[str]:
    """Series names from every `*DESCRIPTORS = [...]` list literal
    under minio_tpu/ (tuple-of-literals entries; first element is the
    name). Parsed from source so the catalog survives broken imports."""
    names: set[str] = set()
    base = os.path.join(root, "minio_tpu")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError, ValueError):
                continue
            for node in ast.walk(tree):
                targets: list = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                if not any(
                    isinstance(t, ast.Name)
                    and t.id.endswith("DESCRIPTORS")
                    for t in targets
                ):
                    continue
                value = getattr(node, "value", None)
                if not isinstance(value, ast.List):
                    continue
                for el in value.elts:
                    if (isinstance(el, ast.Tuple) and el.elts
                            and isinstance(el.elts[0], ast.Constant)
                            and isinstance(el.elts[0].value, str)):
                        names.add(el.elts[0].value)
    return frozenset(names)


class MetricsLint:
    name = "metrics-lint"

    def __init__(self):
        self._catalog: frozenset[str] | None = None

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        if rel in _EXEMPT:
            return False
        return rel.startswith("minio_tpu/") or rel == "bench.py"

    def catalog(self) -> frozenset[str]:
        if self._catalog is None:
            self._catalog = _catalog_names(repo_root())
        return self._catalog

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        catalog = self.catalog()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _WRITE_METHODS:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not isinstance(first, ast.Constant) \
                    or not isinstance(first.value, str):
                continue  # dynamic name: unverifiable statically
            series = first.value
            if series in catalog:
                continue
            if ctx.annotation(KEY, node.lineno) is not None:
                continue
            yield Finding(
                rule=self.name,
                path=ctx.relpath,
                line=node.lineno,
                col=node.col_offset,
                scope=ctx.scope_of(node),
                message=(
                    f"series {series!r} written via .{func.attr}() has "
                    "no descriptor in the metrics_v2 catalog — add a "
                    "(name, type, help) entry to a *DESCRIPTORS list "
                    "or annotate `# metrics-ok: <reason>`"
                ),
                snippet=ctx.line_text(node.lineno),
            )


RULE = MetricsLint()
