"""shm-lint: statically prove the zero-payload-over-pipe invariant.

The worker plane's whole performance story (PR7/PR8) rests on one
fact: shared-memory segments carry the payload, the pipe carries only
names, offsets and verdict ints. One careless reply tuple —
``("ok", strip.data[:n].tobytes())`` — silently reintroduces a full
payload pickle per batch and the copy floor is gone. This rule proves
the invariant over ``pipeline/workers.py`` by taint dataflow:

- **sources** — the payload regions of shm segments: attribute loads
  of ``.data`` / ``.parity`` / ``.digests`` / ``.view`` / ``.buf``,
  the ``recon_src`` / ``recon_out`` / ``recon_digests`` region views,
  and ``np.frombuffer(...)`` results;
- **propagation** — through assignments (def-use chains), tuple/list
  packing, subscripts/attributes of tainted values, method calls ON a
  tainted receiver (``.tobytes()``, ``.reshape()`` — a copy of
  payload bytes is still payload bytes on the pipe), and same-module
  function calls via two summaries computed to fixpoint: does the
  callee's return taint, and which callee params receive tainted
  arguments anywhere in the module;
- **sinks** — anything that serializes onto the pipe: ``pickle.dump``
  / ``dumps``, ``marshal.dump(s)``, and ``.send(...)`` (the worker
  channel). A tainted value reaching a sink fires.

Ordinary calls with tainted arguments return CLEAN
(``hash_strided_digests(data, ...)`` consumes payload, its return is
a digest count) — that asymmetry is what lets the rule prove the
real reply tuples clean instead of drowning in false positives.
Waive a deliberate site with ``# shm-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil, dataflow
from .engine import Finding

KEY = "shm"

SCOPE = "minio_tpu/pipeline/workers.py"

_PAYLOAD_ATTRS = {"data", "parity", "digests", "view", "buf"}
_REGION_METHODS = {"recon_src", "recon_out", "recon_digests"}
_SOURCE_CALLS = {"frombuffer"}
_SINK_DUMPS = {"dump", "dumps"}
_SINK_METHODS = {"send"}


class ShmLint:
    name = "shm-lint"

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/") == SCOPE

    def check(self, ctx: astutil.ModuleContext) -> Iterator[Finding]:
        fns = [n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # Module-wide fixpoint over (return-taints, param-taints):
        # bounded by module size; converges in 2-3 passes here.
        ret_taint: set[str] = set()
        param_taint: dict[str, set[str]] = {}
        for _ in range(4):
            changed = False
            for fn in fns:
                scan = _TaintScan(ctx, ret_taint, param_taint)
                scan.run(fn, report=False)
                if scan.returns_tainted and fn.name not in ret_taint:
                    ret_taint.add(fn.name)
                    changed = True
                for callee, idx in scan.tainted_call_params:
                    names = _param_names(fns, callee, idx)
                    if names - param_taint.get(callee, set()):
                        param_taint.setdefault(callee, set()).update(names)
                        changed = True
            if not changed:
                break
        for fn in fns:
            scan = _TaintScan(ctx, ret_taint, param_taint)
            scan.run(fn, report=True)
            yield from scan.findings


def _param_names(fns: list, callee: str, idx: int) -> set[str]:
    for fn in fns:
        if fn.name == callee:
            args = fn.args.posonlyargs + fn.args.args
            if 0 <= idx < len(args):
                return {args[idx].arg}
    return set()


class _TaintScan:
    """One function's taint pass. Statements execute in source order —
    taint only ever grows, so a simple ordered walk (descending into
    every compound body) reaches the same fixpoint as a full CFG walk
    for a may-analysis, with loop bodies walked twice for
    loop-carried taint."""

    def __init__(self, ctx, ret_taint: set[str],
                 param_taint: dict[str, set[str]]):
        self.ctx = ctx
        self.ret_taint = ret_taint
        self.param_taint = param_taint
        self.tainted: set[str] = set()
        self.returns_tainted = False
        self.tainted_call_params: list[tuple[str, int]] = []
        self.findings: list[Finding] = []
        self._report = False
        self._seen: set[tuple] = set()

    def run(self, fn, report: bool) -> None:
        self._report = report
        self.tainted = set(self.param_taint.get(fn.name, ()))
        body = fn.body
        self._walk(body)
        self._walk(body)  # second pass: loop-carried / late-def taint

    # -- expression taint ----------------------------------------------------

    def _is_tainted(self, expr) -> bool:
        """Structural VALUE taint: does evaluating `expr` yield payload
        bytes (or a container holding them)? A call with tainted
        arguments is CLEAN unless it is a known source, a taint-
        returning module function, or a method on a tainted receiver —
        `hash_strided_digests(data, ...)` consumes payload, its return
        does not carry it."""
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _PAYLOAD_ATTRS \
                    and isinstance(expr.ctx, ast.Load):
                return True
            # Attribute OF a tainted object (arr.ctypes) stays tainted;
            # scalar metadata attrs (strip.name) on a CLEAN receiver
            # stay clean.
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self._is_tainted(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self._is_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return any(self._is_tainted(v) for v in expr.values
                       if v is not None)
        if isinstance(expr, ast.Call):
            name = astutil.call_name(expr)
            if name in _SOURCE_CALLS or name in _REGION_METHODS:
                return True
            if isinstance(expr.func, ast.Name) \
                    and name in self.ret_taint:
                return True
            if isinstance(expr.func, ast.Attribute) \
                    and self._is_tainted(expr.func.value):
                # .tobytes()/.reshape()/[:] of payload stays payload.
                return True
            return False
        if isinstance(expr, ast.BinOp):
            return self._is_tainted(expr.left) \
                or self._is_tainted(expr.right)
        if isinstance(expr, ast.IfExp):
            return self._is_tainted(expr.body) \
                or self._is_tainted(expr.orelse)
        if isinstance(expr, (ast.Starred, ast.Await, ast.NamedExpr)):
            return self._is_tainted(expr.value)
        return False

    # -- statement walk ------------------------------------------------------

    def _walk(self, body: list) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, ast.Assign):
            if self._is_tainted(stmt.value):
                for name in dataflow.assigned_names(
                        stmt.targets[0] if len(stmt.targets) == 1
                        else ast.Tuple(elts=list(stmt.targets),
                                       ctx=ast.Store())):
                    self.tainted.add(name.id)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if self._is_tainted(stmt.value):
                for name in dataflow.assigned_names(stmt.target):
                    self.tainted.add(name.id)
        elif isinstance(stmt, ast.AugAssign):
            if self._is_tainted(stmt.value) \
                    and isinstance(stmt.target, ast.Name):
                self.tainted.add(stmt.target.id)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None and self._is_tainted(stmt.value):
                self.returns_tainted = True
        # Sinks + inter-procedural arg flow, anywhere in the statement.
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._check_call(node)
        # Descend into compound statements (loops twice for carried
        # taint — cheap, and dedupe keeps findings single).
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                self._walk(sub)
        for h in getattr(stmt, "handlers", []):
            self._walk(h.body)

    def _check_call(self, call: ast.Call) -> None:
        name = astutil.call_name(call)
        dotted = astutil.dotted_name(call.func)
        is_sink = (
            (name in _SINK_DUMPS
             and dotted.split(".", 1)[0] in ("pickle", "marshal"))
            or (isinstance(call.func, ast.Attribute)
                and name in _SINK_METHODS)
        )
        if is_sink:
            for arg in list(call.args) + [kw.value for kw in
                                          call.keywords]:
                if self._is_tainted(arg):
                    self._emit(call, name)
                    break
        # Tainted args into same-module functions feed the param-taint
        # summary (resolved by the module fixpoint loop).
        if isinstance(call.func, ast.Name):
            for i, arg in enumerate(call.args):
                if self._is_tainted(arg):
                    self.tainted_call_params.append((call.func.id, i))

    def _emit(self, call: ast.Call, sink: str) -> None:
        key = (call.lineno, call.col_offset)
        if key in self._seen:
            return
        self._seen.add(key)
        if not self._report:
            return
        if self.ctx.annotation(KEY, call.lineno) is not None:
            return
        self.findings.append(Finding(
            rule="shm-lint", path=self.ctx.relpath, line=call.lineno,
            col=call.col_offset, scope=self.ctx.scope_of(call),
            message=(
                f"a value aliasing shm payload (ShmStrip/ShmRing "
                f"region) flows into pipe serialization '.{sink}()' — "
                f"the zero-payload-over-pipe invariant: the pipe "
                f"carries names, offsets and verdicts only; waive "
                f"with '# shm-ok: <reason>'"
            ),
            snippet=self.ctx.line_text(call.lineno),
        ))


RULE = ShmLint()
